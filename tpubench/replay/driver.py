"""``tpubench replay`` — re-drive a recorded bundle through the stack.

The driver rebuilds a bundle's scenario hermetically and runs it through
whatever SYSTEM configuration the caller brought:

* **arrivals** ride the existing ``trace`` schedule kind (the recorded
  timeline lands in a temp trace file) at the recorded rate/duration/
  seed/tenant/class map, so every serve RNG stream — tenant map, class
  assignment, per-tenant Zipf draws — reproduces the original schedule
  exactly;
* the **object population** rebuilds via
  ``FakeBackend.from_population`` (names + sizes + generations from the
  bundle; contents from ``deterministic_bytes``), wrapped with the same
  tail-tolerance + retry layers ``open_backend`` applies everywhere;
* **faults** re-arm via :class:`FaultPlan`, scaled by the chaos plane's
  ``scaled_fault_dict`` discipline (same TPUBENCH_BENCH_SLEEP_SCALE
  contract, so a replayed incident keeps the incident's shape);
* **membership** entries feed ``_ElasticServe`` through
  ``serve.membership_timeline`` untouched.

Scenario knobs come FROM the bundle; system knobs (workers, QoS,
admission, readahead, cache, transport, coop) stay with the caller's
config — replaying under the original fingerprint is the regression
check, replaying under a different one is the A/B. The result carries
``extra["replay"]``: original vs replayed scorecards plus their diff.
"""

from __future__ import annotations

import json
import os
import tempfile

from tpubench.config import (
    BenchConfig,
    FaultConfig,
    parse_sleep_scale,
    validate_fault_config,
)
from tpubench.metrics.report import RunResult
from tpubench.replay.bundle import (
    config_fingerprint,
    distill_baseline,
    distill_drill,
    drill_diff,
    scorecard_diff,
)


def _scenario_config(cfg: BenchConfig, bundle: dict,
                     trace_path: str) -> BenchConfig:
    """The replay run's config: the caller's SYSTEM half with the
    bundle's SCENARIO half written over it (a deep copy — the caller's
    config must survive, the serve A/B reuse discipline)."""
    rcfg = BenchConfig.from_dict(cfg.to_dict())
    sc = rcfg.serve
    sc.arrival = "trace"
    sc.trace_path = trace_path
    sc.rate_rps = float(bundle["rate_rps"])
    sc.duration_s = float(bundle["duration_s"])
    sc.seed = int(bundle["seed"])
    sc.tenants = int(bundle["tenants"])
    sc.alpha = float(bundle["alpha"])
    sc.chunk_bytes = int(bundle["chunk_bytes"])
    sc.classes = [dict(c) for c in bundle["classes"]]
    member = bundle.get("membership") or {}
    sc.hosts = int(member.get("hosts", 1))
    sc.membership_timeline = [
        [float(t0), float(t1), dict(spec)]
        for t0, t1, spec in member.get("timeline") or ()
    ]
    sc.resize_window_s = float(member.get("resize_window_s", 1.0))
    rcfg.workload.object_name_prefix = bundle["object_prefix"]
    rcfg.workload.bucket = bundle["bucket"]
    # The UNSCALED fault plan lands in the config (what the journal's
    # own replay stamp re-records); the ARMED plan is scaled below.
    rcfg.transport.fault = FaultConfig(**(bundle.get("fault") or {}))
    validate_fault_config(rcfg.transport.fault, "bundle fault")
    drill = bundle.get("drill") or None
    if drill:
        # The incident plan and checkpoint shape are scenario, not
        # system: a drill bundle replays the SAME kill/join/save/restore
        # script under the caller's stack. Unknown plan keys (newer
        # bundle) are refused by validate_bundle's field check upstream;
        # here only knobs this build knows are folded.
        dc = rcfg.drill
        for k, v in (drill.get("plan") or {}).items():
            if hasattr(dc, k):
                setattr(dc, k, v)
        lc = rcfg.lifecycle
        for k, v in (drill.get("checkpoint") or {}).items():
            if hasattr(lc, k):
                setattr(lc, k, v)
    return rcfg


def run_replay(cfg: BenchConfig, bundle: dict, tracer=None) -> RunResult:
    """Re-drive ``bundle`` under ``cfg``'s system knobs and stamp the
    replay-vs-original scorecard into ``extra["replay"]``. Hermetic by
    construction (the chaos rule): the fault plane and the recorded
    population live in the fake backend/servers, so only ``fake`` and
    endpoint-less ``http`` targets replay."""
    from tpubench.storage import RetryingBackend, open_backend, wrap_tail
    from tpubench.storage.base import ObjectMeta, read_object_through
    from tpubench.storage.fake import FakeBackend, FaultPlan
    from tpubench.workloads.chaos import (
        scaled_fault_dict,
        spawn_hermetic_server,
    )
    from tpubench.workloads.serve import run_serve

    is_drill = bundle.get("workload") == "drill"

    proto = cfg.transport.protocol
    if proto not in ("fake", "http") or (
        proto == "http" and cfg.transport.endpoint
    ):
        raise SystemExit(
            "replay: hermetic protocols only (fake, or http[--http2] "
            f"against the in-process fake server), not {proto!r} with "
            f"endpoint {cfg.transport.endpoint!r} — the recorded "
            "population and fault plane live in the fake backend/servers"
        )

    objects = [
        ObjectMeta(str(name), int(size), int(gen))
        for name, size, gen in bundle["objects"]
    ]
    if not objects:
        raise SystemExit(
            f"replay: bundle {bundle.get('name')!r} records an empty "
            "object population — nothing to serve"
        )

    fd, trace_path = tempfile.mkstemp(
        prefix="tpubench-replay-", suffix=".json"
    )
    with os.fdopen(fd, "w") as f:
        json.dump(list(bundle["arrivals"]), f)
    rcfg = _scenario_config(cfg, bundle, trace_path)

    scale = parse_sleep_scale("replay timeline durations")
    plan = FaultPlan(
        **scaled_fault_dict(dict(bundle.get("fault") or {}), scale)
    )
    store = FakeBackend.from_population(objects, fault=plan)

    server = None
    backend = None
    try:
        if proto == "http":
            server = spawn_hermetic_server(rcfg, store=store)
            backend = open_backend(rcfg, tracer=tracer)
        else:
            # The open_backend wrapping, applied to the recorded
            # population: tail tolerance INSIDE retry, exactly as every
            # live run gets it — a replay must not skip the layers the
            # original served through.
            inner = wrap_tail(
                store, rcfg.transport.tail,
                chunk_bytes=rcfg.workload.granule_bytes,
            )
            backend = inner if rcfg.transport.retry.policy == "never" \
                else RetryingBackend(inner, rcfg.transport.retry)
        # Warm-up before arming (the chaos discipline): bring-up costs
        # must not land inside the replayed timeline's first seconds.
        try:
            read_object_through(
                backend.open_read(objects[0].name),
                memoryview(bytearray(min(objects[0].size,
                                         rcfg.workload.granule_bytes))),
            )
        except Exception:  # noqa: BLE001 — the run will surface it
            pass
        plan.arm()
        replay_source = {
            "name": bundle["name"],
            "fingerprint": bundle["config_fingerprint"],
            "baseline": bundle["baseline"],
        }
        if is_drill:
            from tpubench.workloads.drill import run_drill

            # The original drill block passes through so re-recording a
            # drill replay reproduces the ORIGINAL bundle (plan and
            # checkpoint shape rebuild identically anyway; the BASELINE
            # must be the original's, not the replay's).
            replay_source["drill"] = bundle.get("drill")

            res = run_drill(
                rcfg, backend=backend, tracer=tracer,
                replay_source=replay_source,
                save_interval_s=(
                    (bundle["drill"].get("plan") or {})
                    .get("save_interval_s")
                ),
            )
        else:
            res = run_serve(
                rcfg, backend=backend, tracer=tracer,
                replay_source=replay_source,
            )
    finally:
        if backend is not None:
            backend.close()
        if server is not None:
            server.stop()
        try:
            os.unlink(trace_path)
        except OSError:
            pass

    s = res.summaries.get("request")
    replayed = distill_baseline(
        res.extra["serve"], errors=res.errors,
        p99_ms=s.p99_ms if s is not None else None,
        membership=res.extra.get("membership"),
    )
    baseline = bundle.get("baseline") or {}
    fp = config_fingerprint(rcfg.to_dict())
    res.workload = "replay"
    res.extra["replay"] = {
        "bundle": bundle["name"],
        "fingerprint": fp,
        "original_fingerprint": bundle["config_fingerprint"],
        "config_match": fp == bundle["config_fingerprint"],
        "arrivals_match": (
            res.extra["serve"].get("arrivals") == len(bundle["arrivals"])
        ),
        "sleep_scale": scale,
        "baseline": baseline,
        "replayed": replayed,
        "diff": scorecard_diff(baseline, replayed),
    }
    if is_drill:
        drill_baseline = (bundle.get("drill") or {}).get("baseline") or {}
        drill_replayed = distill_drill(res.extra.get("drill") or {})
        res.extra["replay"]["drill"] = {
            "baseline": drill_baseline,
            "replayed": drill_replayed,
            "diff": drill_diff(drill_baseline, drill_replayed),
        }
    return res
