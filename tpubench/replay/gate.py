"""``tpubench report --fail-on`` — the exit-code regression contract.

A ``--fail-on`` expression is a FAILURE CONDITION over the metrics a
result document carries: ``<metric><op><threshold>`` (no spaces), e.g.
``--fail-on 'goodput_retention<0.9'`` makes ``tpubench report`` exit
non-zero when a replay retained less than 90 % of the original's
goodput. Repeatable; any violated expression fails the report. Exit
codes: 0 = every gate holds, 1 = a gate tripped, 2 = a named metric
exists in none of the documents (a typo'd gate must fail CI loudly,
never silently pass).

:func:`metric_namespace` is the one definition of which names are
gateable and where they come from — knee/SLO/goodput/staging/rewarm/
retention across serve, sweep, chaos, membership, bench-cell and replay
documents, with replay's diff metrics merged last (a replay doc's
``goodput_retention`` is the replay-vs-original ratio, not the chaos
fault-window one).
"""

from __future__ import annotations

from typing import Iterable, Optional, Sequence

# Two-character operators first: "<=" must never parse as "<" + "=0.9".
_OPS = ("<=", ">=", "==", "!=", "<", ">")


def parse_fail_on(expr: str) -> tuple[str, str, float]:
    """Split ``<metric><op><threshold>``; one-line SystemExit on
    malformed grammar (the config-validation discipline)."""
    for op in _OPS:
        if op in expr:
            metric, _, rhs = expr.partition(op)
            metric = metric.strip()
            rhs = rhs.strip()
            if not metric or any(o in metric for o in _OPS):
                break
            try:
                threshold = float(rhs)
            except ValueError:
                raise SystemExit(
                    f"report --fail-on {expr!r}: threshold {rhs!r} is "
                    "not a number"
                ) from None
            return metric, op, threshold
    raise SystemExit(
        f"report --fail-on {expr!r}: expected <metric><op><threshold> "
        f"with op one of {', '.join(_OPS)} (e.g. 'gold_slo<0.95')"
    )


def _holds(value: float, op: str, threshold: float) -> bool:
    return {
        "<": value < threshold,
        ">": value > threshold,
        "<=": value <= threshold,
        ">=": value >= threshold,
        "==": value == threshold,
        "!=": value != threshold,
    }[op]


def _put(ns: dict, name: str, value) -> None:
    if isinstance(value, bool):
        ns[name] = 1.0 if value else 0.0
    elif isinstance(value, (int, float)):
        ns[name] = float(value)


def metric_namespace(doc: dict) -> dict:
    """Flatten one result document into gateable ``name -> float``
    pairs. Later sources override earlier ones on a name collision —
    replay diff metrics land LAST by design."""
    ns: dict = {}
    if not isinstance(doc, dict):
        return ns
    _put(ns, "gbps", doc.get("gbps"))
    _put(ns, "errors", doc.get("errors"))
    req = (doc.get("summaries") or {}).get("request") or {}
    _put(ns, "p50_ms", req.get("p50_ms"))
    _put(ns, "p99_ms", req.get("p99_ms"))
    # Bench-cell / driver-wrapper documents (bench.py output lines).
    _put(ns, "value", doc.get("value"))
    _put(ns, "staging_efficiency", doc.get("staging_efficiency"))
    extra = doc.get("extra") or {}
    stg = extra.get("staging") or {}
    _put(ns, "staging_efficiency", stg.get("staging_efficiency"))
    sv = extra.get("serve") or {}
    for k in ("goodput_gbps", "achieved_rps", "offered_rps", "arrivals",
              "completed", "shed", "jain_fairness"):
        _put(ns, k, sv.get(k))
    classes = sv.get("classes") or {}
    gold_name = None
    if classes:
        gold_name, gold = min(
            classes.items(), key=lambda kv: kv[1].get("priority", 0)
        )
        _put(ns, "gold_slo", gold.get("slo_attainment"))
        _put(ns, "gold_p99_ms", gold.get("p99_ms"))
    knee = (sv.get("sweep") or {}).get("knee") or {}
    _put(ns, "knee_rps", knee.get("offered_rps"))
    dr = extra.get("drill") or {}
    if dr:
        rst = dr.get("restore") or {}
        _put(ns, "time_to_restore_s", rst.get("time_to_restore_s"))
        _put(ns, "restore_verified", rst.get("verified"))
        _put(ns, "restore_errors", rst.get("errors"))
        _put(ns, "restore_torn_rereads", rst.get("torn_rereads"))
        _put(ns, "restore_forced_direct", rst.get("forced_direct"))
        _put(ns, "time_to_rewarm_s", dr.get("time_to_rewarm_s"))
        saves = dr.get("saves") or {}
        _put(ns, "save_cas_conflicts", saves.get("cas_conflicts"))
        _put(ns, "save_errors", saves.get("errors"))
        _put(ns, "save_bytes_uploaded", saves.get("bytes_uploaded"))
        amp = dr.get("amplification") or {}
        _put(ns, "origin_amplification", amp.get("ratio"))
        slo = dr.get("gold_slo") or {}
        if gold_name is not None:
            _put(
                ns, "drill_gold_slo_restore",
                (slo.get("restore_window") or {}).get(gold_name),
            )
            _put(
                ns, "drill_gold_slo_steady",
                (slo.get("steady") or {}).get(gold_name),
            )
    dknee = (extra.get("drill_sweep") or {}).get("knee") or {}
    _put(ns, "save_knee_rps", dknee.get("offered_rps"))
    mb = extra.get("membership") or {}
    if mb:
        rewarms = [
            ev.get("time_to_rewarm_s") for ev in mb.get("events", ())
            if ev.get("time_to_rewarm_s") is not None
        ]
        if rewarms:
            _put(ns, "rewarm_s", max(rewarms))
        _put(ns, "failovers", mb.get("failovers"))
    chaos = (extra.get("chaos") or {}).get("scorecard") or {}
    for k in ("goodput_retention", "p99_inflation", "time_to_recover_s",
              "failed_reads"):
        _put(ns, k, chaos.get(k))
    rp = extra.get("replay") or {}
    if rp:
        _put(ns, "config_match", rp.get("config_match"))
        _put(ns, "arrivals_match", rp.get("arrivals_match"))
        for k, v in (rp.get("replayed") or {}).items():
            _put(ns, k, v)
        # The diff wins every collision: in a replay document,
        # goodput_retention MEANS replay-vs-original.
        for k, v in (rp.get("diff") or {}).items():
            _put(ns, k, v)
        drp = rp.get("drill") or {}
        for k, v in (drp.get("replayed") or {}).items():
            _put(ns, k, v)
        for k, v in (drp.get("diff") or {}).items():
            _put(ns, k, v)
    return ns


def run_fail_on(
    exprs: Sequence[str],
    docs: Iterable,
    paths: Optional[Sequence[str]] = None,
) -> tuple[int, list[str]]:
    """Evaluate every expression over every document. Returns
    ``(exit_code, report_lines)``: 2 (unknown metric) dominates 1
    (violated gate) dominates 0 — a gate that can't even be looked up
    is the worse CI failure."""
    parsed = [parse_fail_on(e) for e in exprs]
    spaces = [metric_namespace(d) for d in docs]
    paths = list(paths or [])
    rc = 0
    lines: list[str] = []
    for (metric, op, threshold), expr in zip(
        parsed, exprs
    ):
        hits = []
        for i, ns in enumerate(spaces):
            if metric not in ns:
                continue
            label = paths[i] if i < len(paths) else f"doc[{i}]"
            hits.append((label, ns[metric]))
        if not hits:
            known = sorted(set().union(*spaces)) if spaces else []
            lines.append(
                f"fail-on {expr!r}: metric {metric!r} not present in "
                "any document (available: "
                + (", ".join(known) if known else "none") + ")"
            )
            rc = 2
            continue
        for label, value in hits:
            if _holds(value, op, threshold):
                lines.append(
                    f"fail-on {expr!r}: TRIPPED by {label} "
                    f"({metric}={value:g})"
                )
                if rc == 0:
                    rc = 1
            else:
                lines.append(
                    f"fail-on {expr!r}: ok for {label} "
                    f"({metric}={value:g})"
                )
    return rc, lines
