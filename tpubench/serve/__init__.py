"""QoS serve plane: per-tenant scheduling for the open-loop traffic
workload (``tpubench serve``).

:mod:`qos` holds the scheduling primitives — tenant population
expansion, the priority admission queue (the PR-5 runnable-queue
admission cap generalized with a priority order and deadline-aware
shedding), and the scorecard math (per-class SLO attainment, Jain
fairness, knee detection). The workload driver lives in
:mod:`tpubench.workloads.serve`.
"""

from tpubench.serve.qos import (  # noqa: F401
    AdmissionQueue,
    Request,
    ShedError,
    Tenant,
    build_tenants,
    find_knee,
    jain_index,
)
