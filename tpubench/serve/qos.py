"""QoS scheduling primitives for the open-loop serve plane.

Three ideas, each with a closed-loop ancestor in the codebase:

* :class:`AdmissionQueue` — the serve plane's runnable queue. The PR-5
  native fetch executor bounded concurrency with a LIVE admission cap
  (`active[0]`: completions stop being refilled past the cap); this
  generalizes that hook for multi-tenant traffic: requests queue in
  **priority order** (priority class first, arrival order within a
  class), at most ``cap`` requests are in service at once (``set_cap``
  is the tune controller's actuator — the same "workers" knob shape),
  and under overload the queue sheds instead of growing without bound —
  lowest-priority-first when the queue limit is hit, and
  **deadline-aware** at pop time (a request that already cannot make
  its deadline is dropped before a worker burns service time on it).
  ``qos=False`` degrades to a plain FIFO with no shedding and no
  priorities: the baseline arm of the QoS A/B.

* Per-class **weighted budgets** — enforced inside the chunk cache
  (owner-tagged entries, weighted eviction) and the prefetcher
  (per-owner byte budgets); this module only computes the budget splits
  from class weights.

* The **scorecard math** — per-class SLO attainment, the Jain fairness
  index over weight-normalized per-tenant goodput, and saturation-knee
  detection over a load sweep's (offered, goodput, p99) points.
"""

from __future__ import annotations

import hashlib
import heapq
import threading
import time
from dataclasses import dataclass, field
from typing import Optional, Sequence

from tpubench.pipeline.cache import ChunkKey


class ShedError(Exception):
    """A request dropped by admission control (queue overload or a
    deadline that can no longer be met). Carries where it was shed."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


@dataclass(frozen=True)
class Tenant:
    """One synthetic tenant: identity + its class's QoS contract."""

    name: str
    cls: str  # priority-class name (the budget/scorecard granularity)
    priority: int  # lower = more important (heap order)
    weight: float  # share of cache/prefetch budgets
    deadline_ms: float  # per-request SLO
    seed: int  # per-tenant popularity stream


def build_tenants(
    classes: Sequence[dict], n_tenants: int, seed: int = 0,
) -> list[Tenant]:
    """Expand the class spec list into ``n_tenants`` tenants, classes
    allotted by ``share`` (largest remainder, so small classes on small
    populations still get their tenant). Class dicts are validated by
    ``config.validate_serve_config`` before they reach here."""
    shares = [float(c["share"]) for c in classes]
    total = sum(shares)
    quotas = [s / total * n_tenants for s in shares]
    counts = [int(q) for q in quotas]
    # Largest remainder; every class with share > 0 gets at least one
    # tenant when the population allows (a class spec that exists but
    # never sends a request would poison the per-class scorecard).
    rem = sorted(
        range(len(classes)), key=lambda i: quotas[i] - counts[i], reverse=True
    )
    for i in rem:
        if sum(counts) >= n_tenants:
            break
        if counts[i] == int(quotas[i]):
            counts[i] += 1
    for i in range(len(classes)):
        if counts[i] == 0 and n_tenants >= len(classes):
            counts[i] = 1
    while sum(counts) > n_tenants:
        counts[counts.index(max(counts))] -= 1
    tenants: list[Tenant] = []
    for ci, c in enumerate(classes):
        for k in range(counts[ci]):
            tenants.append(Tenant(
                name=f"{c['name']}-{k}",
                cls=str(c["name"]),
                priority=int(c.get("priority", ci)),
                weight=float(c.get("weight", 1.0)),
                deadline_ms=float(c["deadline_ms"]),
                # Collision-free per-tenant popularity seed: an
                # arithmetic mix (seed*10k + ci*1k + k) collides once a
                # class exceeds its block and would hand distinct
                # tenants bit-identical Zipf streams — hash the triple
                # instead (blake2b: deterministic across processes,
                # unlike salted str hash()).
                seed=int.from_bytes(
                    hashlib.blake2b(
                        f"{seed}/{ci}/{k}".encode(), digest_size=8
                    ).digest(), "big",
                ),
            ))
    return tenants


def class_budget_split(classes: Sequence[dict], total_bytes: int) -> dict:
    """Weighted split of a byte budget across priority classes (the
    cache/prefetch budget maps): ``budget_i = total * w_i / Σw``."""
    if total_bytes <= 0:
        return {}
    wsum = sum(float(c.get("weight", 1.0)) for c in classes) or 1.0
    return {
        str(c["name"]): max(1, int(
            total_bytes * float(c.get("weight", 1.0)) / wsum
        ))
        for c in classes
    }


@dataclass
class Request:
    """One open-loop request: a tenant asking for one chunk."""

    tenant: Tenant
    key: ChunkKey
    arrival_s: float  # virtual schedule time (seconds from run start)
    enqueue_ns: int = 0  # real clock at push (deadline anchor)
    seq: int = 0
    index: int = 0  # position in the merged schedule (prefetch cursor)
    # Elastic pod: the front-end host this arrival was dispatched to
    # (-1 = single-host plane / no live host at dispatch time). A
    # worker that finds the host dead at pop time fails over.
    host: int = -1

    @property
    def deadline_ns(self) -> int:
        return self.enqueue_ns + int(self.tenant.deadline_ms * 1e6)


class AdmissionQueue:
    """Priority admission with a live cap and deadline-aware shedding
    (class docstring at module top).

    Workers call :meth:`pop` (blocking) and :meth:`done` when the
    request finishes; the dispatcher calls :meth:`push`. ``close()``
    wakes every waiter; remaining queued requests drain as sheds
    (``shed-drain`` — an open-loop run ends on the clock, and work
    still queued at the bell was NOT served: silently discarding it
    would inflate SLO attainment exactly under overload, where it
    matters)."""

    def __init__(self, *, cap: int, qos: bool = True,
                 queue_limit: int = 0,
                 clock_ns=time.perf_counter_ns,
                 on_shed=None):
        self._cap = max(1, int(cap))
        self.qos = qos
        self.queue_limit = max(0, int(queue_limit))
        self._clock_ns = clock_ns
        # Shed observer (flight breadcrumbs): called for EVERY shed —
        # queue overload, deadline, drain — on whichever thread shed.
        # Errors are swallowed; a breadcrumb must not shed twice.
        self._on_shed = on_shed
        self._cond = threading.Condition()
        self._heap: list[tuple[tuple, Request]] = []
        self._seq = 0
        self._in_service = 0
        self._closed = False
        # Per-class shed ledger: reason -> {cls: count}.
        self.shed: dict[str, dict[str, int]] = {
            "queue": {}, "deadline": {}, "drain": {},
        }
        self.pushed = 0
        self.popped = 0
        self.peak_queue = 0
        self.peak_in_service = 0

    # ------------------------------------------------------------- stats --
    def shed_count(self, cls: Optional[str] = None) -> int:
        n = 0
        for by_cls in self.shed.values():
            if cls is None:
                n += sum(by_cls.values())
            else:
                n += by_cls.get(cls, 0)
        return n

    def stats(self) -> dict:
        with self._cond:
            return {
                "qos": self.qos,
                "cap": self._cap,
                "queue_limit": self.queue_limit,
                "pushed": self.pushed,
                "popped": self.popped,
                "peak_queue": self.peak_queue,
                "peak_in_service": self.peak_in_service,
                "shed": {k: dict(v) for k, v in self.shed.items()},
                "shed_total": self.shed_count(),
            }

    # --------------------------------------------------------------- cap --
    @property
    def cap(self) -> int:
        return self._cap

    def set_cap(self, n: int) -> None:
        """Live admission-cap actuation (the tune controller's knob —
        the PR-5 executor hook shape): a grow wakes parked workers
        immediately; a shrink takes effect as in-service requests
        complete (never a mid-request cancel)."""
        with self._cond:
            self._cap = max(1, int(n))
            self._cond.notify_all()

    # -------------------------------------------------------------- shed --
    def _shed_locked(self, req: Request, reason: str) -> None:
        by = self.shed[reason]
        by[req.tenant.cls] = by.get(req.tenant.cls, 0) + 1
        if self._on_shed is not None:
            try:
                self._on_shed(req, reason)
            except Exception:  # noqa: BLE001 — observer, never the valve
                pass

    # -------------------------------------------------------------- push --
    def push(self, req: Request) -> bool:
        """Enqueue an arrival. Returns False when the request was shed
        at the door (queue overload — QoS mode only: the VICTIM is the
        lowest-priority queued request, which may be an earlier arrival
        rather than this one; False then means *a* request was shed and
        this one queued in its place when it outranks the victim)."""
        with self._cond:
            if self._closed:
                self._shed_locked(req, "drain")
                return False
            req.seq = self._seq = self._seq + 1
            if not req.enqueue_ns:
                req.enqueue_ns = self._clock_ns()
            order = (
                (req.tenant.priority, req.seq) if self.qos else (0, req.seq)
            )
            heapq.heappush(self._heap, (order, req))
            self.pushed += 1
            self.peak_queue = max(self.peak_queue, len(self._heap))
            admitted = True
            if (
                self.qos and self.queue_limit
                and len(self._heap) > self.queue_limit
            ):
                # Overload valve: drop the LOWEST-priority queued entry
                # (latest arrival within the class) — the best-effort
                # tenant absorbs the shed so the high-priority queue
                # stays short. Without QoS the queue just grows: the
                # baseline arm measures what unbounded queueing does to
                # everyone's p99.
                idx = max(
                    range(len(self._heap)), key=lambda i: self._heap[i][0]
                )
                _, victim = self._heap[idx]
                self._heap[idx] = self._heap[-1]
                self._heap.pop()
                heapq.heapify(self._heap)
                self._shed_locked(victim, "queue")
                admitted = victim is not req
            self._cond.notify()
            return admitted

    # --------------------------------------------------------------- pop --
    def pop(self, timeout: Optional[float] = None) -> Optional[Request]:
        """Next request for a service worker: highest priority first,
        admitted only while in-service < cap. QoS mode sheds requests
        whose deadline already passed at pop time (the work is doomed;
        serving it would only delay requests that can still make
        theirs). Returns None on close-and-empty or timeout."""
        # Injected-clock discipline (the tune-controller rule, enforced
        # by `tpubench check`): the wait budget runs on the same
        # clock_ns= the deadline decisions use, so tests/replay can
        # drive both with virtual time.
        deadline_ns = (
            None if timeout is None
            else self._clock_ns() + int(timeout * 1e9)
        )
        stalled_waits = 0
        with self._cond:
            while True:
                while self._heap and self._in_service < self._cap:
                    _, req = heapq.heappop(self._heap)
                    if self.qos and self._clock_ns() > req.deadline_ns:
                        self._shed_locked(req, "deadline")
                        continue
                    self._in_service += 1
                    self.peak_in_service = max(
                        self.peak_in_service, self._in_service
                    )
                    self.popped += 1
                    return req
                if self._closed:
                    return None
                if deadline_ns is not None:
                    remaining = (deadline_ns - self._clock_ns()) / 1e9
                    if remaining <= 0:
                        return None
                    before_ns = self._clock_ns()
                    notified = self._cond.wait(remaining)
                    if notified or self._clock_ns() > before_ns:
                        stalled_waits = 0
                        continue
                    # Condition.wait expires on REAL time; with a
                    # stalled virtual clock_ns= the remaining budget
                    # would never shrink and pop would spin forever.
                    # One zero-progress expiry loops back (a push's
                    # notify can race the expiry, and a coarse-stepped
                    # replay clock may advance just late) — the heap is
                    # re-examined at the loop top; a second consecutive
                    # one means nobody is driving the clock: honor the
                    # timeout.
                    stalled_waits += 1
                    if stalled_waits >= 2:
                        return None
                else:
                    self._cond.wait()

    def done(self) -> None:
        with self._cond:
            self._in_service = max(0, self._in_service - 1)
            self._cond.notify()

    # ------------------------------------------------------------- close --
    def close(self) -> int:
        """End of run: wake every waiter and drain still-queued requests
        as ``drain`` sheds (returned count) — see class docstring."""
        with self._cond:
            self._closed = True
            drained = 0
            while self._heap:
                _, req = heapq.heappop(self._heap)
                self._shed_locked(req, "drain")
                drained += 1
            self._cond.notify_all()
            return drained

    @property
    def queued(self) -> int:
        with self._cond:
            return len(self._heap)

    @property
    def in_service(self) -> int:
        with self._cond:
            return self._in_service


# --------------------------------------------------------------- scorecard --


def jain_index(values: Sequence[float]) -> Optional[float]:
    """Jain's fairness index ``(Σx)² / (n·Σx²)`` over per-tenant (or
    per-class) allocations: 1.0 = perfectly fair, 1/n = one tenant took
    everything. Tenants with zero allocation are legitimate samples
    (they were starved — that IS unfairness); an all-zero or empty set
    has no fairness story and returns None instead of dividing by
    zero."""
    vals = [float(v) for v in values]
    if not vals:
        return None
    sq = sum(v * v for v in vals)
    if sq <= 0:
        return None
    s = sum(vals)
    return (s * s) / (len(vals) * sq)


@dataclass
class ClassLedger:
    """Per-priority-class accounting a serve run accumulates (one
    instance per class, worker-merged under the scorecard lock)."""

    arrivals: int = 0
    completed: int = 0
    deadline_met: int = 0
    shed: int = 0
    errors: int = 0
    bytes: int = 0
    latency_ms: list = field(default_factory=list)

    def slo_attainment(self) -> Optional[float]:
        """Completed-within-deadline over ARRIVALS: a shed request is an
        SLO miss (the tenant asked; the system said no). None for a
        class that saw no traffic — zero arrivals is no evidence, and
        0/0 must not render as either 0% or 100%."""
        if self.arrivals <= 0:
            return None
        return self.deadline_met / self.arrivals


def find_knee(points: Sequence[dict], *, p99_factor: float = 2.0,
              goodput_slack: float = 0.9) -> Optional[dict]:
    """Locate the saturation knee on a load-sweep curve.

    ``points`` are per-load-step dicts carrying ``offered_rps``,
    ``achieved_rps`` and ``p99_ms`` (sorted by offered load by the
    caller). The knee is the FIRST point where the system stops keeping
    up with offered load: p99 inflates past ``p99_factor ×`` the
    lightest point's p99, or achieved throughput falls below
    ``goodput_slack ×`` offered. Returns ``{"index", "offered_rps",
    "reason"}`` or None when the sweep never saturates (the curve's
    whole range is below the knee)."""
    pts = [p for p in points if p.get("offered_rps")]
    if len(pts) < 2:
        return None
    base_p99 = None
    for p in pts:
        if p.get("p99_ms") is not None:
            base_p99 = p["p99_ms"]
            break
    for i, p in enumerate(pts):
        p99 = p.get("p99_ms")
        if (
            base_p99 and p99 is not None and i > 0
            and p99 > p99_factor * base_p99
        ):
            return {
                "index": i, "offered_rps": p["offered_rps"],
                "reason": "p99_inflection",
                "p99_ms": p99, "base_p99_ms": base_p99,
            }
        ach = p.get("achieved_rps")
        if ach is not None and ach < goodput_slack * p["offered_rps"]:
            return {
                "index": i, "offered_rps": p["offered_rps"],
                "reason": "goodput_saturation",
                "achieved_rps": ach,
            }
    return None
