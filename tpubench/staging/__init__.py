"""GCS→HBM staging (SURVEY §2.5.4, §7 step 4 — the north-star delta).

The reference discards downloaded bytes into host RAM (``io.Discard``,
``main.go:140``). Here each filled granule is landed in TPU HBM:

* ``device_put`` path — async host→HBM DMA via ``jax.device_put`` over a
  ring of host slots (double-buffered so fetch overlaps DMA — the I/O analog
  of pipeline parallelism, SURVEY §2.6 PP row);
* ``pallas`` path — a Pallas copy kernel as the alternative landing proof
  (:mod:`tpubench.staging.pallas_stage`).
"""

from tpubench.staging.device import DevicePutStager, make_sink_factory  # noqa: F401
