"""Host→HBM staging via ``jax.device_put`` with a slot ring + granule
aggregation.

Pipeline shape (per worker): the network reader fills host slot *k* while
slots *k-1, k-2, …* are in flight to HBM — fetch ∥ DMA overlap, bounded by
``depth`` (backpressure blocks the reader when every slot is in flight).

Granule aggregation: fetch granules (reference: 2 MB, main.go:123-125) are
packed into ``slot_bytes``-sized slots and shipped with ONE ``device_put``
per slot. Host→HBM transfer engines have a per-transfer fixed cost, so
slot size — not granule size — sets the transfer efficiency: measured on
TPU v5e, 2 MB transfers reach ~1.47 GB/s vs ~1.79 GB/s for 8-16 MB, an
~20% headline difference. The fetch granule stays small (socket-sized
reads, fine-grained first-byte stamps); only the HBM shipping unit grows.

Slots are fixed-size and lane-aligned so every ``device_put`` ships the
same static shape ``(slot_bytes//lane, lane) uint8`` — no per-transfer
recompilation and a layout XLA tiles directly (lane = 128, the TPU lane
width).

Latency accounting: per slot we record (transfer-complete − submit) ns in
the ``stage`` histogram — with overlap this includes queueing, which is the
quantity that matters for pipeline sizing. Total staged bytes / wall gives
the staged GB/s the bench reports.

Integrity: optional mod-2³² byte-sum checksum computed on-device (jitted
accumulate over landed slots) vs. on-host, proving the bytes in HBM are
the bytes fetched (``validate_checksum`` in StagingConfig). Partial slots
are zero-padded at launch so the device sum sees only real bytes.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Optional

import numpy as np

import jax
import jax.numpy as jnp

from tpubench.config import BenchConfig, StagingConfig
from tpubench.mem.slab import SlabLease
from tpubench.metrics.recorder import LatencyRecorder
from tpubench.obs import flight as _flight


@jax.jit
def _accum_checksum(acc, x):
    # mod-2^32 byte sum; uint32 wraps naturally.
    return acc + jnp.sum(x.astype(jnp.uint32))


class GranuleAggregator:
    """Shared zero-copy sink protocol: granules pack into ``_slot_bytes``
    slots; one ``_launch()`` per slot ships it. Concrete stagers provide
    ``_launch`` (ship the first ``_fill`` bytes of the current slot and
    reset ``_fill``), ``_free_view`` (memoryview of the current slot from
    ``_fill``), and optionally ``_precommit(n)`` (inspect the next ``n``
    committed bytes before the fill mark moves)."""

    _fill: int
    _granule: int
    _slot_bytes: int

    def _launch(self) -> None:  # pragma: no cover - abstract
        raise NotImplementedError

    def _free_view(self) -> memoryview:  # pragma: no cover - abstract
        raise NotImplementedError

    def _precommit(self, n: int) -> None:
        pass

    def acquire(self) -> memoryview:
        """At least one granule of free slot space; a slot whose remainder
        is smaller than a granule ships now (slightly under-full) — the
        fetcher is never asked to do sub-granule socket reads."""
        if self._slot_bytes - self._fill < self._granule and self._fill > 0:
            self._launch()
        return self._free_view()

    def commit(self, n: int) -> None:
        """Advance the fill mark over the first ``n`` bytes of the space
        handed out by :meth:`acquire` (which the fetcher filled in place);
        launches the slot when full."""
        if n > 0:
            self._precommit(n)
        self._fill += n
        if self._fill >= self._slot_bytes:
            self._launch()

    def submit(self, mv) -> None:
        """Slot-fill path (granule was filled elsewhere): read the source
        into slot free space, launching transfers as slots fill. Accepts
        any bytes-like source or a :class:`~tpubench.mem.slab.SlabLease`
        — the pipeline's pinned chunk slabs feed the ring directly, with
        no ``bytes`` materialization in between (the caller keeps its
        lease reference until submit returns; the fill is synchronous)."""
        if isinstance(mv, SlabLease):
            mv = mv.view()
        elif not isinstance(mv, memoryview):
            mv = memoryview(mv)
        off = 0
        n = len(mv)
        while off < n:
            dst = self.acquire()
            take = min(len(dst), n - off)
            dst[:take] = mv[off : off + take]
            self.commit(take)
            off += take

    def flush(self) -> None:
        """Ship any partially-filled slot now (end of stream)."""
        if self._fill > 0:
            self._launch()


class DevicePutStager(GranuleAggregator):
    """One per worker. Two sink protocols:

    * copying — ``submit(mv)`` copies the filled granule into the current
      slot's free space (launching transfers as slots fill);
    * zero-copy — ``acquire()`` hands out the current slot's free space for
      the fetch path to fill *in place* (native HTTP receive / ``readinto``
      land bytes directly in the staging slot), then ``commit(n)`` advances
      the fill mark and launches the slot's async host→HBM transfer once
      full — no intermediate Python-held copy (SURVEY hard-part (a):
      socket → pinned buffer → HBM).

    Slots are native posix_memalign'd :class:`AlignedBuffer`\\ s (DLPack/
    numpy zero-copy views) when the C++ engine is available, plain numpy
    otherwise.
    """

    def __init__(
        self,
        worker_id: int,
        granule_bytes: int,
        cfg: Optional[StagingConfig] = None,
        device=None,
        depth: Optional[int] = None,
        slot_bytes: Optional[int] = None,
    ):
        cfg = cfg or StagingConfig()
        self.cfg = cfg
        devices = jax.local_devices()
        self.device = device if device is not None else devices[worker_id % len(devices)]
        self.n_chips = len(devices)
        lane = cfg.lane
        if depth is None:
            depth = max(1, cfg.depth) if cfg.double_buffer else 1
        self._granule = granule_bytes
        # Slot capacity: the aggregation target (but never smaller than one
        # granule), rounded up to a lane multiple so the landed shape is
        # static and lane-aligned; unfilled tails are zero-padded at launch
        # so checksums see only real bytes. ``slot_bytes`` overrides the
        # config (make_sink_factory passes the host-budget-capped value).
        if slot_bytes is None:
            slot_bytes = cfg.slot_bytes
        slot_bytes = max(slot_bytes, granule_bytes)
        self._slot_bytes = ((slot_bytes + lane - 1) // lane) * lane
        self._shape = (self._slot_bytes // lane, lane)
        self._native_bufs = []
        self._slots = []
        engine = None
        if cfg.native_slots:
            from tpubench.native.engine import get_engine

            engine = get_engine()
        for _ in range(depth):
            if engine is not None:
                buf = engine.alloc(self._slot_bytes)
                self._native_bufs.append(buf)
                arr = buf.as_2d(lane)
                arr[:] = 0
                self._slots.append(arr)
            else:
                self._slots.append(np.zeros(self._shape, dtype=np.uint8))
        self.native_slots = engine is not None
        self._slot_views = [memoryview(s.reshape(-1)) for s in self._slots]
        self._futures: list[Optional[jax.Array]] = [None] * depth
        self._submit_ns = [0] * depth
        self._true_bytes = [0] * depth
        self._k = 0
        self._fill = 0  # bytes of real payload in the current slot
        self.depth = depth
        self.staged_bytes = 0
        self.transfers = 0
        # Phase accounting for the pipeline-gap breakdown (round-5 task
        # #1). transfer_wait_ns is always FETCH-THREAD time blocked on
        # transfers (backpressure waits + inline drains). put_submit_ns
        # semantics depend on the drain mode: inline → fetch-thread time
        # inside device_put (wall − wait − submit ≈ fetch+overhead, and
        # the depth-1 serial model falls out); thread → DRAINER-thread
        # time in submit+start, CONCURRENT with fetch (never subtract it
        # from the fetch thread's wall — gap_breakdown branches on the
        # reported drain mode).
        self.transfer_wait_ns = 0
        self.put_submit_ns = 0
        self.stage_recorder = LatencyRecorder(f"w{worker_id}/stage")
        # Flight recorder: one record per SLOT transfer (enqueue →
        # hbm_staged) on the run's ambient recorder. Slot records are the
        # honest per-phase hbm_staged source — slots aggregate granules
        # across reads, so a per-READ hbm_staged stamp would be fiction.
        # Ring ownership: inline drains run on the fetch thread, threaded
        # drains on the drainer — exactly one appender either way.
        self._flight = _flight.active_worker(f"w{worker_id}/stage")
        self._validate = cfg.validate_checksum
        self._host_sum = np.uint64(0)
        self._dev_sum = None
        if self._validate:
            self._dev_sum = jax.device_put(jnp.zeros((), jnp.uint32), self.device)
        # Threaded drain: a per-worker drainer owns block_until_ready so the
        # fetch thread never pays transfer-completion time (both sides
        # release the GIL → true fetch ∥ transfer overlap). Validation keeps
        # inline drains: the checksum accumulate must read the landed array
        # before the slot is reused, which is an ordering the ring's inline
        # backpressure provides for free.
        self._drain_thread = (
            cfg.drain == "thread" and depth > 1 and not self._validate
        )
        self._drain_q: Optional[queue.Queue] = None
        self._drain_err: Optional[BaseException] = None
        self._slot_free: list[threading.Event] = []
        self._drainer: Optional[threading.Thread] = None
        if self._drain_thread:
            self._drain_q = queue.Queue()
            self._slot_free = [threading.Event() for _ in range(depth)]
            for e in self._slot_free:
                e.set()
            self._drainer = threading.Thread(
                target=self._drain_loop, name=f"w{worker_id}-drain", daemon=True
            )
            self._drainer.start()

    # ------------------------------------------------------------ pipeline --
    def _drain_loop(self) -> None:
        """Drainer thread: SUBMITS and completes transfers in launch
        order. Submission lives here, not in ``_launch``, because on some
        runtimes (measured: the tunneled axon backend) ``device_put``
        performs the whole transfer inside the submission call — a
        fetch-thread submit would serialize fetch and transfer exactly
        like the depth-1 ring and the "overlap" label would buy nothing.
        Both sides release the GIL in their hot paths (numpy/socket copies
        here, PJRT transfer there), so fetch ∥ transfer is real. All
        accounting this thread mutates is read by the fetch thread only
        after :meth:`finish` joins it."""
        assert self._drain_q is not None
        while True:
            item = self._drain_q.get()
            if item is None:
                return
            k, nbytes, enqueue_ns = item
            try:
                submit_ns = time.perf_counter_ns()
                fut = jax.device_put(self._slots[k], self.device)
                self.put_submit_ns += time.perf_counter_ns() - submit_ns
                fut.block_until_ready()
                # Stage latency from ENQUEUE, not dequeue: with overlap
                # the queueing behind earlier slots is part of the
                # quantity that sizes the pipeline (module docstring).
                done_ns = time.perf_counter_ns()
                self.stage_recorder.record_ns(done_ns - enqueue_ns)
                if self._flight is not None:
                    op = self._flight.begin(
                        "slot", "device_put", enqueue_ns=enqueue_ns,
                        install=False, kind="stage",
                    )
                    op.mark("hbm_staged", done_ns)
                    op.finish(nbytes)
                self.staged_bytes += nbytes
            except BaseException as e:  # re-raised at the next acquire
                if self._drain_err is None:
                    self._drain_err = e
            finally:
                self._slot_free[k].set()

    def _drain_slot(self, k: int) -> None:
        fut = self._futures[k]
        if fut is None:
            return
        t0 = time.perf_counter_ns()
        fut.block_until_ready()
        done_ns = time.perf_counter_ns()
        self.transfer_wait_ns += done_ns - t0
        self.stage_recorder.record_ns(done_ns - self._submit_ns[k])
        if self._flight is not None:
            op = self._flight.begin(
                "slot", "device_put", enqueue_ns=self._submit_ns[k],
                install=False, kind="stage",
            )
            op.mark("hbm_staged", done_ns)
            op.finish(self._true_bytes[k])
        self.staged_bytes += self._true_bytes[k]
        if self._validate:
            self._dev_sum = _accum_checksum(self._dev_sum, fut)
            # The accumulate reads `fut`, which on zero-copy backends (CPU)
            # may alias the host slot we are about to overwrite — force it to
            # complete before the slot is released. Validation mode trades
            # overlap for integrity; the perf path has _validate off.
            self._dev_sum.block_until_ready()
        self._futures[k] = None

    def _launch(self) -> None:
        """Ship the current slot (``_fill`` real bytes) to HBM and rotate
        the ring. The next slot's prior transfer is drained lazily by the
        next :meth:`acquire` — the backpressure point."""
        k = self._k
        slot = self._slots[k]
        if self._fill < self._slot_bytes:
            # Partial slot (end of run / oversized granule remainder): zero
            # the tail so checksum/pad semantics stay exact. Full slots —
            # the steady state — skip this memset.
            slot.reshape(-1)[self._fill :] = 0
        self.transfers += 1
        if self._drain_thread:
            # Hand the FILLED slot to the drainer, which submits AND
            # completes the transfer (see _drain_loop): the fetch thread
            # pays neither, only the slot_free backpressure wait.
            self._slot_free[k].clear()
            self._drain_q.put((k, self._fill, time.perf_counter_ns()))
        else:
            submit_ns = time.perf_counter_ns()
            fut = jax.device_put(slot, self.device)
            self.put_submit_ns += time.perf_counter_ns() - submit_ns
            self._submit_ns[k] = submit_ns
            self._futures[k] = fut
            self._true_bytes[k] = self._fill
        self._fill = 0
        self._k = (k + 1) % self.depth
        if self.depth == 1:
            # Single slot = fully synchronous staging: complete the transfer
            # before the fetcher can touch the slot again.
            self._drain_slot(k)

    def _free_view(self) -> memoryview:
        """Completing the current slot's prior in-flight transfer here is
        the ring's backpressure point (wait on the drainer, or drain
        inline)."""
        k = self._k
        if self._drain_thread:
            if not self._slot_free[k].is_set():
                t0 = time.perf_counter_ns()
                self._slot_free[k].wait()
                self.transfer_wait_ns += time.perf_counter_ns() - t0
            if self._drain_err is not None:
                # A failed transfer must abort the fetch NOW: the drainer
                # frees slots on failure (no deadlock), so without this
                # check backpressure never engages and a dead device
                # would let the fetch burn the whole measurement window.
                raise self._drain_err
        else:
            self._drain_slot(k)
        return self._slot_views[k][self._fill :]

    def _precommit(self, n: int) -> None:
        if self._validate:
            k = self._k
            chunk = self._slots[k].reshape(-1)[self._fill : self._fill + n]
            self._host_sum += chunk.sum(dtype=np.uint64)

    def finish(self) -> dict:
        # Slot buffers are released even when a drain failed (a failed
        # worker must not leak depth × slot_bytes of pinned native memory
        # while the run's other failure domains keep going) — but only
        # after every in-flight transfer has settled, failed or not, so no
        # transfer can touch freed memory.
        err: Optional[BaseException] = None
        try:
            self.flush()
        except BaseException as e:
            err = e
        if self._drain_thread:
            # The tail of the transfer time is paid here (waiting for the
            # drainer to complete in-flight slots): without counting it,
            # the overlap config's gap breakdown would report near-zero
            # transfer wait and dump all transfer time into "fetch".
            t0 = time.perf_counter_ns()
            self._drain_q.put(None)
            self._drainer.join()
            self.transfer_wait_ns += time.perf_counter_ns() - t0
            if err is None:
                err = self._drain_err
        else:
            for k in range(self.depth):
                try:
                    self._drain_slot(k)
                except BaseException as e:
                    if err is None:
                        err = e
        self._slot_views = []
        self._slots = []
        for buf in self._native_bufs:
            buf.free()
        self._native_bufs = []
        if err is not None:
            raise err
        stats = {
            "staged_bytes": self.staged_bytes,
            "transfers": self.transfers,
            "slot_bytes": self._slot_bytes,
            "n_chips": self.n_chips,
            "native_slots": self.native_slots,
            "drain": "thread" if self._drain_thread else "inline",
            "stage_recorder": self.stage_recorder,
            "device": str(self.device),
            "transfer_wait_ns": self.transfer_wait_ns,
            "put_submit_ns": self.put_submit_ns,
        }
        if self._validate:
            dev = int(jax.device_get(self._dev_sum))
            host = int(self._host_sum % np.uint64(2**32))
            stats["checksum_ok"] = dev == host
            stats["checksum_device"] = dev
            stats["checksum_host"] = host
        return stats


class LockedSink:
    """Serialization wrapper for a slot ring shared by CONCURRENT
    producers.

    A :class:`GranuleAggregator` is single-producer by construction:
    ``acquire``/``commit`` mutate the fill mark and ring cursor
    non-atomically, so two unsynchronized producers could be handed the
    SAME slot region (double-assign) and silently corrupt each other's
    bytes. This wrapper makes each ``submit`` — the whole
    acquire→fill→commit transaction — atomic under one lock.

    No production path shares a ring today — train-ingest's step loop is
    the stager's only producer (the prefetcher fills the HOST cache, not
    the ring), and every other workload keeps one stager per worker.
    This is the designated wrapper for a pipeline that does fan multiple
    producers into one ring (e.g. staging prefetched chunks from the
    prefetch workers directly); the double-assign test in
    ``test_staging.py`` pins the invariant it must then provide.

    Deliberately does NOT forward the zero-copy ``acquire``/``commit``
    pair: a lock released between acquire and commit would re-open the
    double-assign window, and holding it across the producer's socket
    read would serialize the fetches the ring exists to overlap. Shared
    rings use the copying ``submit`` path; the workload's
    ``hasattr(sink, "acquire")`` probe then routes correctly on its own.
    """

    def __init__(self, inner):
        self._inner = inner
        self._lock = threading.Lock()

    def submit(self, mv: memoryview) -> None:
        with self._lock:
            self._inner.submit(mv)

    def finish(self) -> dict:
        with self._lock:
            return self._inner.finish()


def budgeted_slot_bytes(cfg: BenchConfig) -> int:
    """slot_bytes scaled so ALL workers' slots fit the host budget (never
    below one granule): 48 reference-default workers must not pin gigabytes
    of aligned memory before the first byte is fetched. Both stagers hold
    a depth-slot ring per worker (pallas gained its ring in round 5)."""
    s = cfg.staging
    depth = max(1, s.depth) if s.double_buffer else 1
    workers = max(1, cfg.workload.workers)
    budget = max(1, s.host_budget_mb) * (1 << 20)
    per_worker = budget // (workers * depth)
    return max(cfg.workload.granule_bytes, min(s.slot_bytes, per_worker))


def make_sink_factory(cfg: BenchConfig) -> Optional[Callable[[int], DevicePutStager]]:
    """Staging sink factory for the read workload, from config."""
    mode = cfg.staging.mode
    if mode == "none":
        return None
    slot = budgeted_slot_bytes(cfg)
    if mode == "device_put":
        return lambda worker_id: DevicePutStager(
            worker_id,
            granule_bytes=cfg.workload.granule_bytes,
            cfg=cfg.staging,
            slot_bytes=slot,
        )
    if mode == "pallas":
        from tpubench.staging.pallas_stage import PallasStager

        return lambda worker_id: PallasStager(
            worker_id,
            granule_bytes=cfg.workload.granule_bytes,
            cfg=cfg.staging,
            slot_bytes=slot,
        )
    raise ValueError(f"unknown staging mode {mode!r} (none|device_put|pallas)")
