"""Host→HBM staging via ``jax.device_put`` with a double-buffered slot ring.

Pipeline shape (per worker): the network reader fills host slot *k* while
slots *k-1, k-2, …* are in flight to HBM — fetch ∥ DMA overlap, bounded by
``depth`` (backpressure blocks the reader when every slot is in flight).
Slots are fixed-size and lane-aligned so every ``device_put`` ships the same
static shape ``(granule//lane, lane) uint8`` — no per-transfer recompilation
and a layout XLA tiles directly (lane = 128, the TPU lane width).

Latency accounting: per granule we record (transfer-complete − submit) ns in
the ``stage`` histogram — with overlap this includes queueing, which is the
quantity that matters for pipeline sizing. Total staged bytes / wall gives
the staged GB/s the bench reports.

Integrity: optional mod-2³² byte-sum checksum computed on-device (jitted
accumulate over landed granules) vs. on-host, proving the bytes in HBM are
the bytes fetched (``validate_checksum`` in StagingConfig).
"""

from __future__ import annotations

import time
from typing import Callable, Optional

import numpy as np

import jax
import jax.numpy as jnp

from tpubench.config import BenchConfig, StagingConfig
from tpubench.metrics.recorder import LatencyRecorder


@jax.jit
def _accum_checksum(acc, x):
    # mod-2^32 byte sum; uint32 wraps naturally.
    return acc + jnp.sum(x.astype(jnp.uint32))


class DevicePutStager:
    """One per worker. Two sink protocols:

    * copying — ``submit(mv)`` copies the filled granule into a free host
      slot and launches the async host→HBM transfer;
    * zero-copy — ``acquire()`` hands out the next free slot's memory for
      the fetch path to fill *in place* (native HTTP receive / ``readinto``
      land bytes directly in the staging slot), then ``commit(n)`` launches
      the transfer with no intermediate Python-held copy (SURVEY hard-part
      (a): socket → pinned buffer → HBM).

    Slots are native posix_memalign'd :class:`AlignedBuffer`\\ s (DLPack/
    numpy zero-copy views) when the C++ engine is available, plain numpy
    otherwise.
    """

    def __init__(
        self,
        worker_id: int,
        granule_bytes: int,
        cfg: Optional[StagingConfig] = None,
        device=None,
        depth: int = 2,
    ):
        cfg = cfg or StagingConfig()
        self.cfg = cfg
        devices = jax.local_devices()
        self.device = device if device is not None else devices[worker_id % len(devices)]
        self.n_chips = len(devices)
        lane = cfg.lane
        # Slot capacity: granule rounded up to a lane multiple (2 MB is
        # already 16384×128); the tail of a short final granule is
        # zero-padded so checksums see only real bytes.
        self._slot_bytes = ((granule_bytes + lane - 1) // lane) * lane
        self._shape = (self._slot_bytes // lane, lane)
        self._native_bufs = []
        self._slots = []
        engine = None
        if getattr(cfg, "native_slots", True):
            from tpubench.native.engine import get_engine

            engine = get_engine()
        for _ in range(depth):
            if engine is not None:
                buf = engine.alloc(self._slot_bytes)
                self._native_bufs.append(buf)
                arr = buf.as_2d(lane)
                arr[:] = 0
                self._slots.append(arr)
            else:
                self._slots.append(np.zeros(self._shape, dtype=np.uint8))
        self.native_slots = engine is not None
        self._slot_views = [memoryview(s.reshape(-1)) for s in self._slots]
        self._futures: list[Optional[jax.Array]] = [None] * depth
        self._submit_ns = [0] * depth
        self._true_bytes = [0] * depth
        self._k = 0
        self.depth = depth
        self.staged_bytes = 0
        self.granules = 0
        self.stage_recorder = LatencyRecorder(f"w{worker_id}/stage")
        self._validate = cfg.validate_checksum
        self._host_sum = np.uint64(0)
        self._dev_sum = None
        if self._validate:
            self._dev_sum = jax.device_put(jnp.zeros((), jnp.uint32), self.device)

    # ------------------------------------------------------------ pipeline --
    def _drain_slot(self, k: int) -> None:
        fut = self._futures[k]
        if fut is None:
            return
        fut.block_until_ready()
        self.stage_recorder.record_ns(time.perf_counter_ns() - self._submit_ns[k])
        self.staged_bytes += self._true_bytes[k]
        if self._validate:
            self._dev_sum = _accum_checksum(self._dev_sum, fut)
            # The accumulate reads `fut`, which on zero-copy backends (CPU)
            # may alias the host slot we are about to overwrite — force it to
            # complete before the slot is released. Validation mode trades
            # overlap for integrity; the perf path has _validate off.
            self._dev_sum.block_until_ready()
        self._futures[k] = None

    def acquire(self) -> memoryview:
        """Zero-copy path: drain the next slot's in-flight transfer (the
        backpressure point) and hand its memory to the fetcher to fill."""
        k = self._k
        self._drain_slot(k)
        return self._slot_views[k]

    def commit(self, n: int) -> None:
        """Stage the first ``n`` bytes of the slot handed out by
        :meth:`acquire` (which the fetcher filled in place)."""
        k = self._k
        slot = self._slots[k]
        flat = slot.reshape(-1)
        if n < self._slot_bytes:
            flat[n:] = 0  # keep checksum/pad semantics exact
        if self._validate:
            self._host_sum += np.uint64(int(flat[:n].astype(np.uint32).sum()))
        self._submit_ns[k] = time.perf_counter_ns()
        self._futures[k] = jax.device_put(slot, self.device)
        self._true_bytes[k] = n
        self.granules += 1
        self._k = (k + 1) % self.depth
        if self.depth == 1:
            # Single-buffered = fully synchronous staging: complete the
            # transfer before returning. (Also the faster path on transports
            # where the sync route beats queued async dispatch.)
            self._drain_slot(k)

    def submit(self, mv: memoryview) -> None:
        """Copying path (granule was filled elsewhere): copy into the next
        free slot, then stage."""
        n = len(mv)
        dst = self.acquire()
        dst[:n] = mv
        self.commit(n)

    def finish(self) -> dict:
        for k in range(self.depth):
            self._drain_slot(k)
        # All transfers complete; native slot memory is safe to release.
        self._slot_views = []
        self._slots = []
        for buf in self._native_bufs:
            buf.free()
        self._native_bufs = []
        stats = {
            "staged_bytes": self.staged_bytes,
            "granules": self.granules,
            "n_chips": self.n_chips,
            "native_slots": self.native_slots,
            "stage_recorder": self.stage_recorder,
            "device": str(self.device),
        }
        if self._validate:
            dev = int(jax.device_get(self._dev_sum))
            host = int(self._host_sum % np.uint64(2**32))
            stats["checksum_ok"] = dev == host
            stats["checksum_device"] = dev
            stats["checksum_host"] = host
        return stats


def make_sink_factory(cfg: BenchConfig) -> Optional[Callable[[int], DevicePutStager]]:
    """Staging sink factory for the read workload, from config."""
    mode = cfg.staging.mode
    if mode == "none":
        return None
    if mode == "device_put":
        return lambda worker_id: DevicePutStager(
            worker_id,
            granule_bytes=cfg.workload.granule_bytes,
            cfg=cfg.staging,
            depth=2 if cfg.staging.double_buffer else 1,
        )
    if mode == "pallas":
        from tpubench.staging.pallas_stage import PallasStager

        return lambda worker_id: PallasStager(
            worker_id,
            granule_bytes=cfg.workload.granule_bytes,
            cfg=cfg.staging,
        )
    raise ValueError(f"unknown staging mode {mode!r} (none|device_put|pallas)")
