"""Host→HBM staging via ``jax.device_put`` with a slot ring + granule
aggregation.

Pipeline shape (per worker): the network reader fills host slot *k* while
slots *k-1, k-2, …* are in flight to HBM — fetch ∥ DMA overlap, bounded by
``depth`` (backpressure blocks the reader when every slot is in flight).
Depth > 1 rides the overlapped staging executor
(:mod:`tpubench.staging.executor`): a depth-K in-flight window whose
reaper thread submits and completes transfers OUT OF ORDER, so the fetch
thread pays transfer time only as backpressure when all K slots are
pending — the ``transfer_wait_s``-killing shape BENCH_r05 motivated.
Depth 1 (and validation mode) keeps the serial inline ring: submit, then
complete on the fetch thread — the A/B comparator the depth sweep
measures the executor against.

Granule aggregation: fetch granules (reference: 2 MB, main.go:123-125) are
packed into ``slot_bytes``-sized slots and shipped with ONE ``device_put``
per slot. Host→HBM transfer engines have a per-transfer fixed cost, so
slot size — not granule size — sets the transfer efficiency: measured on
TPU v5e, 2 MB transfers reach ~1.47 GB/s vs ~1.79 GB/s for 8-16 MB, an
~20% headline difference. The fetch granule stays small (socket-sized
reads, fine-grained first-byte stamps); only the HBM shipping unit grows.

Slots are fixed-size and lane-aligned so every ``device_put`` ships the
same static shape ``(slot_bytes//lane, lane) uint8`` — no per-transfer
recompilation and a layout XLA tiles directly (lane = 128, the TPU lane
width).

Latency accounting: per slot we record (transfer-complete − submit) ns in
the ``stage`` histogram — with overlap this includes queueing, which is the
quantity that matters for pipeline sizing. Total staged bytes / wall gives
the staged GB/s the bench reports.

Integrity: optional mod-2³² byte-sum checksum computed on-device (jitted
accumulate over landed slots) vs. on-host, proving the bytes in HBM are
the bytes fetched (``validate_checksum`` in StagingConfig). Partial slots
are zero-padded at launch so the device sum sees only real bytes.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

import numpy as np

import jax
import jax.numpy as jnp

from tpubench.config import BenchConfig, StagingConfig
from tpubench.mem.slab import SlabLease
from tpubench.metrics.recorder import LatencyRecorder
from tpubench.obs import flight as _flight
from tpubench.staging.executor import InflightWindow, TransferEngine
from tpubench.staging.stats import staging_efficiency


@jax.jit
def _accum_checksum(acc, x):
    # mod-2^32 byte sum; uint32 wraps naturally.
    return acc + jnp.sum(x.astype(jnp.uint32))


class GranuleAggregator:
    """Shared zero-copy sink protocol: granules pack into ``_slot_bytes``
    slots; one ``_launch()`` per slot ships it. Concrete stagers provide
    ``_launch`` (ship the first ``_fill`` bytes of the current slot and
    reset ``_fill``), ``_free_view`` (memoryview of the current slot from
    ``_fill``), and optionally ``_precommit(n)`` (inspect the next ``n``
    committed bytes before the fill mark moves)."""

    _fill: int
    _granule: int
    _slot_bytes: int

    def _launch(self) -> None:  # pragma: no cover - abstract
        raise NotImplementedError

    def _free_view(self) -> memoryview:  # pragma: no cover - abstract
        raise NotImplementedError

    def _precommit(self, n: int) -> None:
        pass

    def acquire(self) -> memoryview:
        """At least one granule of free slot space; a slot whose remainder
        is smaller than a granule ships now (slightly under-full) — the
        fetcher is never asked to do sub-granule socket reads."""
        if self._slot_bytes - self._fill < self._granule and self._fill > 0:
            self._launch()
        return self._free_view()

    def commit(self, n: int) -> None:
        """Advance the fill mark over the first ``n`` bytes of the space
        handed out by :meth:`acquire` (which the fetcher filled in place);
        launches the slot when full."""
        if n > 0:
            self._precommit(n)
        self._fill += n
        if self._fill >= self._slot_bytes:
            self._launch()

    def submit(self, mv) -> None:
        """Slot-fill path (granule was filled elsewhere): read the source
        into slot free space, launching transfers as slots fill. Accepts
        any bytes-like source or a :class:`~tpubench.mem.slab.SlabLease`
        — the pipeline's pinned chunk slabs feed the ring directly, with
        no ``bytes`` materialization in between (the caller keeps its
        lease reference until submit returns; the fill is synchronous)."""
        if isinstance(mv, SlabLease):
            mv = mv.view()
        elif not isinstance(mv, memoryview):
            mv = memoryview(mv)
        off = 0
        n = len(mv)
        while off < n:
            dst = self.acquire()
            take = min(len(dst), n - off)
            dst[:take] = mv[off : off + take]
            self.commit(take)
            off += take

    def flush(self) -> None:
        """Ship any partially-filled slot now (end of stream)."""
        if self._fill > 0:
            self._launch()


class DevicePutStager(GranuleAggregator):
    """One per worker. Two sink protocols:

    * copying — ``submit(mv)`` copies the filled granule into the current
      slot's free space (launching transfers as slots fill);
    * zero-copy — ``acquire()`` hands out the current slot's free space for
      the fetch path to fill *in place* (native HTTP receive / ``readinto``
      land bytes directly in the staging slot), then ``commit(n)`` advances
      the fill mark and launches the slot's async host→HBM transfer once
      full — no intermediate Python-held copy (SURVEY hard-part (a):
      socket → pinned buffer → HBM).

    Slots are native posix_memalign'd :class:`AlignedBuffer`\\ s (DLPack/
    numpy zero-copy views) when the C++ engine is available, plain numpy
    otherwise.
    """

    def __init__(
        self,
        worker_id: int,
        granule_bytes: int,
        cfg: Optional[StagingConfig] = None,
        device=None,
        depth: Optional[int] = None,
        slot_bytes: Optional[int] = None,
        transfer_engine: Optional[TransferEngine] = None,
    ):
        cfg = cfg or StagingConfig()
        self.cfg = cfg
        devices = jax.local_devices()
        self.device = device if device is not None else devices[worker_id % len(devices)]
        self.n_chips = len(devices)
        lane = cfg.lane
        if depth is None:
            depth = max(1, cfg.depth) if cfg.double_buffer else 1
        self._granule = granule_bytes
        # Slot capacity: the aggregation target (but never smaller than one
        # granule), rounded up to a lane multiple so the landed shape is
        # static and lane-aligned; unfilled tails are zero-padded at launch
        # so checksums see only real bytes. ``slot_bytes`` overrides the
        # config (make_sink_factory passes the host-budget-capped value).
        if slot_bytes is None:
            slot_bytes = cfg.slot_bytes
        slot_bytes = max(slot_bytes, granule_bytes)
        self._slot_bytes = ((slot_bytes + lane - 1) // lane) * lane
        self._shape = (self._slot_bytes // lane, lane)
        self._alloc_engine = None
        if cfg.native_slots:
            from tpubench.native.engine import get_engine

            self._alloc_engine = get_engine()
        self._native_bufs = []
        self._slots: list = []
        self._slot_views: list[memoryview] = []
        self._lane = lane
        for _ in range(depth):
            self._alloc_slot()
        self.native_slots = self._alloc_engine is not None
        self._fill = 0  # bytes of real payload in the current slot
        self.depth = depth
        self.transfers = 0
        self.stage_recorder = LatencyRecorder(f"w{worker_id}/stage")
        # Flight recorder: one record per transfer (enqueue → stage_submit
        # → stage_complete/hbm_staged) on the run's ambient recorder.
        # Slot records are the honest per-phase hbm_staged source — slots
        # aggregate granules across reads, so a per-READ hbm_staged stamp
        # would be fiction. Ring ownership: serial drains append on the
        # fetch thread, overlapped completions on the window's reaper —
        # exactly one appender either way.
        self._flight = _flight.active_worker(f"w{worker_id}/stage")
        self._validate = cfg.validate_checksum
        self._host_sum = np.uint64(0)
        self._dev_sum = None
        if self._validate:
            self._dev_sum = jax.device_put(jnp.zeros((), jnp.uint32), self.device)
        # Overlapped executor (staging/executor.py): a depth-K in-flight
        # window whose reaper submits and completes transfers out of
        # order. Validation keeps the serial inline ring: the checksum
        # accumulate must read the landed array before the slot is
        # reused, an ordering inline backpressure provides for free.
        # Depth 1 is the fully synchronous comparator by definition.
        self._overlap = depth > 1 and not self._validate
        # Public: workloads branch on this (an overlapped submit returns
        # before the bytes land, so a step-level hbm_staged stamp at
        # submit time would be fiction — the window's per-transfer
        # records carry the honest completion stamp instead).
        self.overlapped = self._overlap
        self._window: Optional[InflightWindow] = None
        # Serial-path state (depth 1 / validation).
        self._futures: list[Optional[jax.Array]] = [None] * depth
        self._submit_ns = [0] * depth
        self._true_bytes = [0] * depth
        self._k = 0
        self.staged_bytes = 0
        # Phase accounting for the pipeline-gap breakdown. transfer_wait_ns
        # is always FETCH-THREAD time blocked on transfers (backpressure
        # waits + inline drains). put_submit_ns semantics depend on the
        # mode: inline → fetch-thread time inside device_put; overlap →
        # REAPER-thread time in submission, CONCURRENT with fetch (never
        # subtract it from the fetch thread's wall — gap_breakdown
        # branches on the reported drain mode).
        self.transfer_wait_ns = 0
        self.put_submit_ns = 0
        self.transfer_flight_ns = 0
        self._inflight_samples: list[int] = []
        if self._overlap:
            self._window = InflightWindow(
                depth, self.device,
                engine=transfer_engine or TransferEngine(),
                stage_recorder=self.stage_recorder,
                flight_ring=self._flight,
                name=f"w{worker_id}",
            )
            # Free-slot pool (out-of-order: availability is a free list,
            # not a rotation); the window's reaper returns slots here.
            self._free_cond = threading.Condition()
            self._free: list[int] = list(range(depth))
            self._retired: list[int] = []
            self._slot_count = depth
            self._target_depth = depth
            self._cur: Optional[int] = None
            self._closed = False

    # ------------------------------------------------------------ slots ----
    def _alloc_slot(self) -> int:
        """Allocate one slot buffer (pinned native when available) and
        return its index."""
        if self._alloc_engine is not None:
            buf = self._alloc_engine.alloc(self._slot_bytes)
            self._native_bufs.append(buf)
            arr = buf.as_2d(self._lane)
            arr[:] = 0
        else:
            arr = np.zeros(self._shape, dtype=np.uint8)
        self._slots.append(arr)
        self._slot_views.append(memoryview(arr.reshape(-1)))
        return len(self._slots) - 1

    def _release_slot(self, k: int) -> None:
        """Reaper callback: the slot's transfer settled (out of order)."""
        with self._free_cond:
            if self._slot_count > self._target_depth:
                # A live shrink retires slots as their transfers land
                # (buffers stay allocated until finish — freeing under a
                # possible in-flight alias would be worse than the RAM).
                self._retired.append(k)
                self._slot_count -= 1
            else:
                self._free.append(k)
            self._free_cond.notify_all()

    def set_depth(self, depth: int) -> int:
        """Live depth actuation (the ``staging_depth`` tune knob; no-op
        narrowing to clamp on the serial path, which has no window)."""
        depth = max(1, int(depth))
        if not self._overlap:
            return self.depth
        with self._free_cond:
            if self._closed:
                # Workers finish at their own pace while the controller
                # keeps probing: a grow fanned onto a torn-down stager
                # must not allocate pinned buffers nothing will free.
                return self.depth
            self._target_depth = depth
            while self._slot_count < depth:
                k = self._retired.pop() if self._retired else self._alloc_slot()
                self._slot_count += 1
                self._free.append(k)
            while self._slot_count > depth and self._free:
                self._retired.append(self._free.pop())
                self._slot_count -= 1
            self._free_cond.notify_all()
        self._window.set_depth(depth)
        self.depth = depth
        return depth

    # ------------------------------------------------------------ pipeline --
    def _drain_slot(self, k: int) -> None:
        fut = self._futures[k]
        if fut is None:
            return
        t0 = time.perf_counter_ns()
        fut.block_until_ready()
        done_ns = time.perf_counter_ns()
        self.transfer_wait_ns += done_ns - t0
        self.transfer_flight_ns += done_ns - self._submit_ns[k]
        self.stage_recorder.record_ns(done_ns - self._submit_ns[k])
        if self._flight is not None:
            op = self._flight.begin(
                "slot", "device_put", enqueue_ns=self._submit_ns[k],
                install=False, kind="stage",
            )
            op.mark("stage_submit", self._submit_ns[k])
            op.mark("stage_complete", done_ns)
            op.mark("hbm_staged", done_ns)
            op.finish(self._true_bytes[k])
        self.staged_bytes += self._true_bytes[k]
        if self._validate:
            self._dev_sum = _accum_checksum(self._dev_sum, fut)
            # The accumulate reads `fut`, which on zero-copy backends (CPU)
            # may alias the host slot we are about to overwrite — force it to
            # complete before the slot is released. Validation mode trades
            # overlap for integrity; the perf path has _validate off.
            self._dev_sum.block_until_ready()
        self._futures[k] = None

    def _launch(self) -> None:
        """Ship the current slot (``_fill`` real bytes) to HBM. Overlap:
        hand the filled slot to the window (reaper submits + completes;
        the fetch thread pays neither). Serial: submit inline and drain
        lazily at the next :meth:`acquire` — the old backpressure
        point."""
        nbytes = self._fill
        self.transfers += 1
        if self._overlap:
            k = self._cur
            slot = self._slots[k]
            if nbytes < self._slot_bytes:
                slot.reshape(-1)[nbytes:] = 0
            self._fill = 0
            self._cur = None
            self._window.enqueue(
                slot, nbytes,
                on_complete=lambda k=k: self._release_slot(k),
                label="slot",
            )
            return
        k = self._k
        slot = self._slots[k]
        if nbytes < self._slot_bytes:
            # Partial slot (end of run / oversized granule remainder): zero
            # the tail so checksum/pad semantics stay exact. Full slots —
            # the steady state — skip this memset.
            slot.reshape(-1)[nbytes:] = 0
        submit_ns = time.perf_counter_ns()
        fut = jax.device_put(slot, self.device)
        self.put_submit_ns += time.perf_counter_ns() - submit_ns
        self._submit_ns[k] = submit_ns
        self._futures[k] = fut
        self._true_bytes[k] = nbytes
        self._inflight_samples.append(
            sum(1 for f in self._futures if f is not None)
        )
        self._fill = 0
        self._k = (k + 1) % self.depth
        if self.depth == 1:
            # Single slot = fully synchronous staging: complete the transfer
            # before the fetcher can touch the slot again.
            self._drain_slot(k)

    def _acquire_slot(self) -> int:
        """Overlap path: a free slot to fill, blocking (= backpressure)
        while every slot's transfer is still pending."""
        with self._free_cond:
            t0 = None
            while not self._free:
                if self._window.error is not None:
                    break
                if t0 is None:
                    t0 = time.perf_counter_ns()
                # Short timeout: a direct-lease transfer failure frees no
                # slot, so the error check above must get to run.
                self._free_cond.wait(0.05)
            if t0 is not None:
                self.transfer_wait_ns += time.perf_counter_ns() - t0
            self._window.raise_if_failed()
            return self._free.pop()

    def _free_view(self) -> memoryview:
        """The ring's backpressure point: a slot to fill, waiting out (or
        inline-draining) a prior transfer when none is free."""
        if self._overlap:
            if self._cur is None:
                self._cur = self._acquire_slot()
            return self._slot_views[self._cur][self._fill :]
        k = self._k
        self._drain_slot(k)
        return self._slot_views[k][self._fill :]

    def submit_owned(self, lease: SlabLease, label: str = "chunk") -> None:
        """Direct zero-copy staging of a pinned slab lease: the transfer
        reads straight out of the slab — no slot copy — and the LEASE'S
        reference (which the caller hands over) is released by the
        window's reaper only when the bytes have landed, never at
        submit. Serial path (depth 1 / validation): degrade to the
        copying slot path, releasing after the synchronous fill."""
        if not self._overlap:
            try:
                self.submit(lease)
            finally:
                lease.release()
            return
        self.transfers += 1
        self._window.enqueue(
            lease.as_numpy(), len(lease), on_complete=lease.release,
            label=label,
        )

    def _precommit(self, n: int) -> None:
        if self._validate:
            k = self._k
            chunk = self._slots[k].reshape(-1)[self._fill : self._fill + n]
            self._host_sum += chunk.sum(dtype=np.uint64)

    def finish(self) -> dict:
        # Slot buffers are released even when a transfer failed (a failed
        # worker must not leak depth × slot_bytes of pinned native memory
        # while the run's other failure domains keep going) — but only
        # after every in-flight transfer has settled, failed or not, so no
        # transfer can touch freed memory.
        err: Optional[BaseException] = None
        try:
            self.flush()
        except BaseException as e:
            err = e
        if self._overlap:
            with self._free_cond:
                self._closed = True  # registry grows become no-ops
            # The tail of the transfer time is paid inside close()'s
            # drain: without counting it, the overlap config's gap
            # breakdown would report near-zero transfer wait and dump
            # all transfer time into "fetch".
            self._window.close()
            if err is None:
                err = self._window.error
        else:
            for k in range(self.depth):
                try:
                    self._drain_slot(k)
                except BaseException as e:
                    if err is None:
                        err = e
        self._slot_views = []
        self._slots = []
        for buf in self._native_bufs:
            buf.free()
        self._native_bufs = []
        if err is not None:
            raise err
        stats = {
            "slot_bytes": self._slot_bytes,
            "n_chips": self.n_chips,
            "native_slots": self.native_slots,
            "drain": "overlap" if self._overlap else "inline",
            "stage_recorder": self.stage_recorder,
            "device": str(self.device),
            "depth": self.depth,
            "transfers": self.transfers,
        }
        if self._overlap:
            w = self._window.stats()
            self.staged_bytes = w["staged_bytes"]
            self.transfer_wait_ns = w["transfer_wait_ns"] + self.transfer_wait_ns
            self.put_submit_ns = w["put_submit_ns"]
            self.transfer_flight_ns = w["transfer_flight_ns"]
            stats.update({
                "staged_bytes": self.staged_bytes,
                "transfer_wait_ns": self.transfer_wait_ns,
                "put_submit_ns": self.put_submit_ns,
                "transfer_flight_ns": self.transfer_flight_ns,
                "inflight_p50": w["inflight_p50"],
                "inflight_max": w["inflight_max"],
                "out_of_order_completions": w["out_of_order_completions"],
            })
        else:
            samples = np.asarray(
                self._inflight_samples or [0], dtype=np.int64
            )
            stats.update({
                "staged_bytes": self.staged_bytes,
                "transfer_wait_ns": self.transfer_wait_ns,
                "put_submit_ns": self.put_submit_ns,
                "transfer_flight_ns": self.transfer_flight_ns,
                "inflight_p50": float(np.percentile(samples, 50)),
                "inflight_max": int(samples.max()),
                "out_of_order_completions": 0,
            })
        stats["staging_efficiency"] = staging_efficiency(
            stats["transfer_wait_ns"], stats["put_submit_ns"],
            stats["transfer_flight_ns"], self._overlap,
        )
        if self._validate:
            dev = int(jax.device_get(self._dev_sum))
            host = int(self._host_sum % np.uint64(2**32))
            stats["checksum_ok"] = dev == host
            stats["checksum_device"] = dev
            stats["checksum_host"] = host
        return stats


class LockedSink:
    """Serialization wrapper for a slot ring shared by CONCURRENT
    producers.

    A :class:`GranuleAggregator` is single-producer by construction:
    ``acquire``/``commit`` mutate the fill mark and ring cursor
    non-atomically, so two unsynchronized producers could be handed the
    SAME slot region (double-assign) and silently corrupt each other's
    bytes. This wrapper makes each ``submit`` — the whole
    acquire→fill→commit transaction — atomic under one lock.

    No production path shares a ring today — train-ingest's step loop is
    the stager's only producer (the prefetcher fills the HOST cache, not
    the ring), and every other workload keeps one stager per worker.
    This is the designated wrapper for a pipeline that does fan multiple
    producers into one ring (e.g. staging prefetched chunks from the
    prefetch workers directly); the double-assign test in
    ``test_staging.py`` pins the invariant it must then provide.

    Deliberately does NOT forward the zero-copy ``acquire``/``commit``
    pair: a lock released between acquire and commit would re-open the
    double-assign window, and holding it across the producer's socket
    read would serialize the fetches the ring exists to overlap. Shared
    rings use the copying ``submit`` path; the workload's
    ``hasattr(sink, "acquire")`` probe then routes correctly on its own.
    """

    def __init__(self, inner):
        self._inner = inner
        self._lock = threading.Lock()

    def submit(self, mv: memoryview) -> None:
        with self._lock:
            self._inner.submit(mv)

    def submit_owned(self, lease, label: str = "chunk") -> None:
        """Direct lease staging stays atomic too: the enqueue mutates the
        window's credit state, and the wrapped stager's transfer counter,
        under the same lock as slot submits."""
        with self._lock:
            self._inner.submit_owned(lease, label=label)

    def flush(self) -> None:
        with self._lock:
            self._inner.flush()

    def set_depth(self, depth: int) -> int:
        """Depth actuation forwards (the tune knob must reach the real
        ring through the wrapper). Not under the submit lock: a shrink
        blocked behind a long submit would stall the controller thread,
        and the stager's own free-list lock already serializes it."""
        return self._inner.set_depth(depth)

    @property
    def depth(self) -> int:
        return self._inner.depth

    @property
    def overlapped(self) -> bool:
        return getattr(self._inner, "overlapped", False)

    def finish(self) -> dict:
        """Forwards the wrapped stager's FULL stats dict — staged bytes,
        stage recorder, and the overlap counters (depth, in-flight gauge,
        staging_efficiency) — so concurrent-producer runs don't lose
        staging metrics behind the wrapper."""
        with self._lock:
            return self._inner.finish()


def budgeted_slot_bytes(cfg: BenchConfig) -> int:
    """slot_bytes scaled so ALL workers' slots fit the host budget (never
    below one granule): 48 reference-default workers must not pin gigabytes
    of aligned memory before the first byte is fetched. Both stagers hold
    a depth-slot ring per worker (pallas gained its ring in round 5)."""
    s = cfg.staging
    depth = max(1, s.depth) if s.double_buffer else 1
    workers = max(1, cfg.workload.workers)
    budget = max(1, s.host_budget_mb) * (1 << 20)
    per_worker = budget // (workers * depth)
    return max(cfg.workload.granule_bytes, min(s.slot_bytes, per_worker))


def make_sink_factory(
    cfg: BenchConfig,
) -> Optional[Callable[[int], DevicePutStager]]:
    """Staging sink factory for the read workload, from config. Live
    ``staging_depth`` actuation is wired by the read workload itself,
    which wraps whatever factory it is handed in a
    :class:`~tpubench.staging.executor.StagerRegistry` attach."""
    mode = cfg.staging.mode
    if mode == "none":
        return None
    slot = budgeted_slot_bytes(cfg)
    if mode == "device_put":
        return lambda worker_id: DevicePutStager(
            worker_id,
            granule_bytes=cfg.workload.granule_bytes,
            cfg=cfg.staging,
            slot_bytes=slot,
        )
    if mode == "pallas":
        from tpubench.staging.pallas_stage import PallasStager

        return lambda worker_id: PallasStager(
            worker_id,
            granule_bytes=cfg.workload.granule_bytes,
            cfg=cfg.staging,
            slot_bytes=slot,
        )
    raise ValueError(f"unknown staging mode {mode!r} (none|device_put|pallas)")
