"""Overlapped staging executor: a depth-K in-flight window over host→HBM
transfers with out-of-order completion.

BENCH_r05's phase breakdowns showed staging wall time was ~85–90%
``transfer_wait_s``: the pipeline fetched asynchronously but still
*waited* on transfers — the depth-1 ring drained inline on the fetch
thread, and the round-5 drainer completed transfers one at a time in
launch order, so at most ONE transfer was ever on the tunnel. This module
replaces both with the DMA-streaming shape (PAPERS.md arXiv 2603.10030):
keep K transfers in flight simultaneously and complete them in whatever
order the tunnel finishes them.

:class:`InflightWindow` is the core: producers (the stager's fetch
thread) ``enqueue`` filled buffers; a single **reaper** thread submits
the ``jax.device_put`` calls (submission must not run on the fetch
thread — on some runtimes, measured on the tunneled axon backend, the
whole transfer happens inside the submission call) and then *polls* the
per-slot futures (``jax.Array.is_ready``), finalizing whichever transfer
lands first — out-of-order completion into the slot ring. Backpressure
is the window credit: ``enqueue`` blocks only when all K slots are
pending, and that blocked time is the run's ``transfer_wait_ns``.

Completion discipline: the completed future is ``.delete()``d
immediately (HBM is released per transfer, not at GC's leisure), and
submission passes ``donate=`` when the runtime supports it so XLA never
re-copies a buffer it can own. Each transfer's resources (a slot to
free, a slab lease to release) are dropped by the reaper at
*completion*, never at submit — a lease handed to the window stays alive
until its bytes have actually landed.

Everything is injectable for tests: :class:`TransferEngine` is the
submit/probe/wait/delete surface (the deterministic fake in
``tests/test_staging.py`` drives completion from a test-controlled
clock), and the clock itself is a parameter.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

import numpy as np

import jax

from tpubench.obs import flight as _flight
from tpubench.staging.stats import staging_efficiency


class TransferEngine:
    """Host→HBM transfer surface the window drives (default: jax).

    ``submit`` starts an async transfer and returns a handle; ``probe``
    is the non-blocking completion check (None = unsupported on this
    runtime, which degrades the reaper to in-order blocking waits —
    never to freeing a buffer a transfer might still read); ``wait``
    blocks until the bytes have landed; ``delete`` releases the landed
    device buffer immediately.
    """

    def __init__(self):
        self._donate_ok = True

    def submit(self, array, device):
        if self._donate_ok:
            try:
                # Donation lets XLA take ownership instead of re-copying
                # when the input is donatable; harmless (ignored) for
                # committed host numpy buffers.
                return jax.device_put(array, device, donate=True)
            except TypeError:  # older jax without donate=
                self._donate_ok = False
        return jax.device_put(array, device)

    def probe(self, handle) -> Optional[bool]:
        is_ready = getattr(handle, "is_ready", None)
        return bool(is_ready()) if is_ready is not None else None

    def wait(self, handle) -> None:
        handle.block_until_ready()

    def delete(self, handle) -> None:
        delete = getattr(handle, "delete", None)
        if delete is not None:
            delete()


class _Transfer:
    """One in-flight transfer: buffer, accounting stamps, and the
    resources the reaper drops at completion."""

    __slots__ = ("array", "nbytes", "on_complete", "op", "enqueue_ns",
                 "seq", "handle", "submit_ns")

    def __init__(self, array, nbytes: int, on_complete, op, enqueue_ns: int,
                 seq: int):
        self.array = array
        self.nbytes = nbytes
        self.on_complete = on_complete  # free the slot / release the lease
        self.op = op  # flight record (kind="stage"), finished by the reaper
        self.enqueue_ns = enqueue_ns
        self.seq = seq
        self.handle = None
        self.submit_ns = 0


class InflightWindow:
    """Depth-K transfer window: submit queue + reaper + OOO completion.

    One window per stager; the stager's fetch thread is the only
    producer, the reaper the only consumer — all counters the reaper
    mutates are read by the producer only under the shared lock or
    after :meth:`close` joins the thread.
    """

    def __init__(
        self,
        depth: int,
        device,
        *,
        engine: Optional[TransferEngine] = None,
        stage_recorder=None,
        flight_ring=None,
        name: str = "stage",
        poll_s: float = 0.0002,
        clock: Callable[[], int] = time.perf_counter_ns,
    ):
        self._engine = engine or TransferEngine()
        self._device = device
        self._depth = max(1, int(depth))
        self._recorder = stage_recorder
        self._ring = flight_ring
        self._poll_s = poll_s
        self._clock = clock
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: list[_Transfer] = []
        self._inflight: list[_Transfer] = []
        self._seq = 0
        self._stop = False
        self.error: Optional[BaseException] = None
        # Accounting (finalized values read after close()).
        self.transfers = 0
        self.staged_bytes = 0
        self.wait_ns = 0  # producer-thread backpressure + drain-tail time
        self.submit_ns = 0  # reaper time inside engine.submit (∥ fetch)
        self.flight_ns = 0  # Σ per-transfer (complete − submit)
        self.out_of_order = 0  # completions that overtook an older submit
        self.inflight_samples: list[int] = []  # gauge, sampled per submit
        self._reaper = threading.Thread(
            target=self._run, name=f"{name}-reaper", daemon=True
        )
        self._reaper.start()

    # ---------------------------------------------------------- producer --
    @property
    def depth(self) -> int:
        return self._depth

    def set_depth(self, depth: int) -> int:
        """Live depth actuation (the ``staging_depth`` tune knob): grow
        widens the credit window immediately; shrink lets in-flight
        transfers drain down to the new bound naturally."""
        with self._cond:
            self._depth = max(1, int(depth))
            self._cond.notify_all()
            return self._depth

    def raise_if_failed(self) -> None:
        """A failed transfer must abort the producer NOW: the reaper
        frees failed transfers' resources (no deadlock), so without
        this check backpressure never engages and a dead device would
        let the fetch burn the whole measurement window."""
        if self.error is not None:
            raise self.error

    def enqueue(self, array, nbytes: int, on_complete=None,
                label: str = "device_put",
                enqueue_ns: Optional[int] = None) -> None:
        """Hand a filled buffer to the window. Blocks (backpressure)
        while K transfers are already pending; the blocked time is
        ``wait_ns`` — the quantity this executor exists to shrink."""
        enq = enqueue_ns if enqueue_ns is not None else self._clock()
        op = None
        if self._ring is not None:
            op = self._ring.begin(
                label, "device_put", enqueue_ns=enq, install=False,
                kind="stage",
            )
            # The serial ring also stamps stage_submit, so the journal
            # needs an explicit marker for window (overlapped) transfers
            # — timeline_summary's `overlapped` counts this note.
            op.note("stage", event="overlap")
        with self._cond:
            t0 = None
            while (len(self._queue) + len(self._inflight) >= self._depth
                   and self.error is None):
                if t0 is None:
                    t0 = self._clock()
                self._cond.wait()
            if t0 is not None:
                self.wait_ns += self._clock() - t0
            if self.error is not None:
                if op is not None:
                    # Abandon, don't finish: finish() appends to the
                    # worker ring, and the reaper may be appending
                    # failed in-flight ops to the SAME ring right now —
                    # the ring is single-appender by design. This
                    # transfer never entered the window; no record.
                    op.abandon()
                if on_complete is not None:
                    on_complete()
                raise self.error
            self._queue.append(
                _Transfer(array, int(nbytes), on_complete, op, enq, self._seq)
            )
            self._seq += 1
            self._cond.notify_all()

    def drain(self) -> None:
        """Block until every enqueued transfer has settled (landed or
        failed). The tail of the transfer time is paid here — without
        counting it into ``wait_ns`` the overlapped config would report
        near-zero transfer wait and dump all transfer time into
        "fetch"."""
        with self._cond:
            t0 = self._clock()
            while self._queue or self._inflight:
                self._cond.wait()
            self.wait_ns += self._clock() - t0

    def close(self) -> None:
        """Drain, stop the reaper, join. Idempotent."""
        self.drain()
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._reaper.join()

    def stats(self) -> dict:
        samples = np.asarray(self.inflight_samples or [0], dtype=np.int64)
        wait, flight = self.wait_ns, self.flight_ns
        efficiency = staging_efficiency(
            wait, self.submit_ns, flight, overlapped=True
        )
        return {
            "depth": self._depth,
            "transfers": self.transfers,
            "staged_bytes": self.staged_bytes,
            "transfer_wait_ns": wait,
            "put_submit_ns": self.submit_ns,
            "transfer_flight_ns": flight,
            "out_of_order_completions": self.out_of_order,
            "inflight_p50": float(np.percentile(samples, 50)),
            "inflight_max": int(samples.max()),
            "staging_efficiency": efficiency,
        }

    # ------------------------------------------------------------ reaper --
    def _run(self) -> None:
        try:
            self._loop()
        except BaseException as e:  # last-resort guard
            # A reaper death without error-marking would deadlock the
            # producer forever (enqueue/drain wait on window credit that
            # can never free). Mark the error, fail every live transfer,
            # and drop their resources so finish() can still tear down.
            with self._cond:
                if self.error is None:
                    self.error = e
                pending = self._queue + self._inflight
                self._queue = []
                self._inflight = []
                self._cond.notify_all()
            for tr in pending:
                if tr.op is not None:
                    tr.op.finish(error=e)
                self._consume_callback(tr)

    @staticmethod
    def _consume_callback(tr: _Transfer) -> None:
        """Run a transfer's on_complete exactly once (slot frees and
        lease releases must never double-fire across failure paths)."""
        cb, tr.on_complete = tr.on_complete, None
        if cb is not None:
            cb()

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._inflight:
                    if self._stop:
                        return
                    self._cond.wait()
                # Snapshot WITHOUT clearing: a queued transfer keeps its
                # window credit until _submit moves it to _inflight, so
                # queued+inflight ≤ depth holds at all times — popping
                # here would let the producer enqueue a fresh depth's
                # worth while these are still on the tunnel.
                batch = list(self._queue)
            for tr in batch:
                self._submit(tr)
            self._complete_ready()

    def _submit(self, tr: _Transfer) -> None:
        try:
            # Adopt the transfer's op (and with it the read's trace
            # position) for the submission call — the same discipline
            # _finalize uses for completion — so submit-side
            # annotations land on the transfer's record, which is the
            # read's "staging transfer" child span in the trace tree.
            if tr.op is not None:
                _flight.adopt_op(tr.op)
            try:
                s0 = self._clock()
                tr.handle = self._engine.submit(tr.array, self._device)
                tr.submit_ns = s0
                self.submit_ns += self._clock() - s0
            finally:
                if tr.op is not None:
                    _flight.adopt_op(None)
        except BaseException as e:  # raised at the producer's next enqueue
            self._fail(tr, e)
            return
        if tr.op is not None:
            tr.op.mark("stage_submit", tr.submit_ns)
        with self._cond:
            self._queue.remove(tr)
            self._inflight.append(tr)
            self.inflight_samples.append(len(self._inflight))

    def _complete_ready(self) -> None:
        """Finalize every READY in-flight transfer, first-landed first
        (out-of-order w.r.t. submission); when nothing is ready yet,
        wait a poll tick (new enqueues interrupt the wait) or — on
        runtimes without a completion probe — block on the oldest."""
        while True:
            with self._cond:
                inflight = list(self._inflight)
                queued = bool(self._queue)
            if not inflight or queued:
                return  # nothing to do, or new submissions take priority
            ready = None
            probed = False
            for tr in inflight:
                ok = self._engine.probe(tr.handle)
                if ok is None:
                    break  # no probe on this runtime: in-order fallback
                probed = True
                if ok:
                    ready = tr
                    break
            if ready is None and not probed:
                # No completion probe on this runtime: block on the
                # oldest (in-order degrade — never free a buffer a
                # transfer might still read). With probe support we must
                # NOT block here: a blocking wait would starve the
                # submission of buffers the producer enqueues meanwhile,
                # serializing the very transfers the window overlaps.
                ready = inflight[0]
            if ready is not None:
                self._finalize(ready)
                continue
            with self._cond:
                if self._queue:
                    return
                self._cond.wait(self._poll_s)

    def _finalize(self, tr: _Transfer) -> None:
        # The whole completion path is guarded, not just wait(): a
        # delete()/recorder failure that escaped would kill the reaper
        # with the transfer still holding window credit.
        try:
            self._engine.wait(tr.handle)
            done = self._clock()
            self.flight_ns += done - tr.submit_ns
            if self._recorder is not None:
                # Stage latency from ENQUEUE, not submit: with overlap
                # the queueing behind earlier transfers is part of the
                # quantity that sizes the pipeline.
                self._recorder.record_ns(done - tr.enqueue_ns)
            if tr.op is not None:
                # The reaper adopts the op (hedge-producer discipline)
                # so completion phases — including hbm_staged, which
                # must stamp when the bytes LAND, not when submit
                # returned — attach on the transfer's record from this
                # helper thread.
                _flight.adopt_op(tr.op)
                try:
                    _flight.note_phase("stage_complete", done)
                    _flight.note_phase("hbm_staged", done)
                    tr.op.finish(tr.nbytes)
                finally:
                    _flight.adopt_op(None)
            self._engine.delete(tr.handle)
        except BaseException as e:
            self._fail(tr, e)
            return
        with self._cond:
            self._inflight.remove(tr)
            self.transfers += 1
            self.staged_bytes += tr.nbytes
            if any(o.seq < tr.seq for o in self._inflight):
                self.out_of_order += 1
            self._cond.notify_all()
        self._consume_callback(tr)

    def _fail(self, tr: _Transfer, e: BaseException) -> None:
        if tr.op is not None:
            tr.op.finish(error=e)
        with self._cond:
            if self.error is None:
                self.error = e
            if tr in self._queue:  # failed inside submit: still queued
                self._queue.remove(tr)
            if tr in self._inflight:
                self._inflight.remove(tr)
            self._cond.notify_all()
        # Resources are freed even on failure (a dead device must not
        # leak slots/leases); the producer aborts via raise_if_failed
        # at its next acquire/enqueue.
        self._consume_callback(tr)


class StagerRegistry:
    """Live-actuation fan-out for the ``staging_depth`` tune knob.

    The read workload builds one stager per worker INSIDE the worker
    threads, after the controller's knob list exists — so the knob
    actuates this registry, and stagers attach as they are created.
    A depth commanded before a stager attached is applied at attach
    (late workers join the tuned operating point, not the config's)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._stagers: list = []
        self._depth: Optional[int] = None

    def attach(self, stager):
        if hasattr(stager, "set_depth"):
            with self._lock:
                self._stagers.append(stager)
                depth = self._depth
            if depth is not None:
                stager.set_depth(depth)
        return stager

    def set_depth(self, depth: int) -> None:
        with self._lock:
            self._depth = int(depth)
            stagers = list(self._stagers)
        for st in stagers:
            st.set_depth(depth)

    def __len__(self) -> int:
        with self._lock:
            return len(self._stagers)
