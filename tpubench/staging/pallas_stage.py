"""Pallas landing kernels — the on-device half of the staging path.

``device_put`` moves granule bytes host→HBM; these kernels are the HBM-side
landing ops, written Pallas-TPU-first:

* :func:`pallas_checksum` — mod-2³² byte-sum reduction of a landed granule,
  tiled (block, 128) through VMEM with an SMEM scalar accumulator.
* :func:`pallas_land` — fused copy+checksum: streams the staged granule
  HBM→VMEM→HBM into the landing buffer while accumulating the checksum.
  The grid pipeline gives the HBM↔VMEM double-buffering for free (the
  idiomatic TPU form of the hand-rolled DMA pattern), so validation costs
  one extra HBM round-trip, not a host readback.

On non-TPU backends (CPU tests) the kernels run in interpret mode; on TPU
they compile via Mosaic. Granules are (rows, 128) uint8 with rows a
multiple of the block size — guaranteed by the stager's lane-aligned slots.
"""

from __future__ import annotations

import functools
import time
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpubench.config import StagingConfig
from tpubench.metrics.recorder import LatencyRecorder
from tpubench.staging.device import GranuleAggregator

LANE = 128
# uint8 min tile is (32, 128); 512 rows = 64 KB/block in VMEM.
BLOCK_ROWS = 512


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


@jax.jit
def _add_u32(a, b):
    # uint32 add wraps mod 2^32 natively — the running device-side
    # checksum accumulator. Jitted: EAGER scalar ops on a tunneled
    # backend dispatch pathologically slowly (measured 6.2 s for one
    # eager stack+sum), while a jitted add compiles once and dispatches
    # async.
    return a + b


def _checksum_kernel(x_ref, out_ref):
    # Mosaic has no unsigned reductions; int32 two's-complement wraparound is
    # exactly mod-2^32 arithmetic, so accumulate signed and bitcast outside.
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_ref[0, 0] = jnp.int32(0)

    out_ref[0, 0] += jnp.sum(x_ref[:].astype(jnp.int32))


@functools.partial(jax.jit, static_argnames=("block_rows",))
def pallas_checksum(x: jax.Array, block_rows: int = BLOCK_ROWS) -> jax.Array:
    rows, lane = x.shape
    assert lane == LANE and rows % block_rows == 0, (rows, lane)
    out = pl.pallas_call(
        _checksum_kernel,
        grid=(rows // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, LANE), lambda i: (i, 0), memory_space=pltpu.VMEM)
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
        out_shape=jax.ShapeDtypeStruct((1, 1), jnp.int32),
        interpret=_interpret(),
    )(x)
    return jax.lax.bitcast_convert_type(out[0, 0], jnp.uint32)


def _land_kernel(x_ref, out_ref, csum_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        csum_ref[0, 0] = jnp.int32(0)

    blk = x_ref[:]
    out_ref[:] = blk
    csum_ref[0, 0] += jnp.sum(blk.astype(jnp.int32))


@functools.partial(jax.jit, static_argnames=("block_rows",))
def pallas_land(x: jax.Array, block_rows: int = BLOCK_ROWS):
    """(landed_copy, checksum) — one pipelined pass over the granule."""
    rows, lane = x.shape
    assert lane == LANE and rows % block_rows == 0, (rows, lane)
    landed, csum = pl.pallas_call(
        _land_kernel,
        grid=(rows // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, LANE), lambda i: (i, 0), memory_space=pltpu.VMEM)
        ],
        out_specs=[
            pl.BlockSpec((block_rows, LANE), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, LANE), jnp.uint8),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
        ],
        interpret=_interpret(),
    )(x)
    return landed, jax.lax.bitcast_convert_type(csum[0, 0], jnp.uint32)


class PallasStager(GranuleAggregator):
    """Staging sink: slot → device_put → fused pallas land (copy+checksum).

    Same sink contract and RING shape as DevicePutStager — granules
    aggregate into ``slot_bytes`` slots; a slot's launch is one async
    ``device_put`` + one async landing-pass dispatch; the ring rotates and
    the PREVIOUS in-flight slot drains lazily at the next ``acquire`` (the
    backpressure point), so fetch and landing overlap up to ``depth``
    slots (round-4 verdict #6: the synchronous single-slot form blocked
    per landing pass and could never contend in the bench A/B). Always
    validates: the checksum is free inside the landing pass.

    ``depth`` follows StagingConfig like the device_put ring
    (``double_buffer``/``depth``; 1 = fully synchronous).
    """

    def __init__(
        self,
        worker_id: int,
        granule_bytes: int,
        cfg: Optional[StagingConfig] = None,
        device=None,
        slot_bytes: Optional[int] = None,
        depth: Optional[int] = None,
    ):
        cfg = cfg or StagingConfig()
        devices = jax.local_devices()
        self.device = device if device is not None else devices[worker_id % len(devices)]
        self.n_chips = len(devices)
        lane = cfg.lane
        assert lane == LANE, "pallas path is lane-128 only"
        self._granule = granule_bytes
        if depth is None:
            depth = max(1, cfg.depth) if cfg.double_buffer else 1
        self.depth = depth
        # Round the aggregation target up so rows divide the kernel block.
        if slot_bytes is None:
            slot_bytes = cfg.slot_bytes
        slot_bytes = max(slot_bytes, granule_bytes)
        block_bytes = BLOCK_ROWS * LANE
        self._slot_bytes = -(-slot_bytes // block_bytes) * block_bytes
        self._shape = (self._slot_bytes // LANE, LANE)
        self._slots = [np.zeros(self._shape, dtype=np.uint8) for _ in range(depth)]
        # Per-slot in-flight landing: (landed, csum, submit_ns, true_bytes).
        self._inflight: list[Optional[tuple]] = [None] * depth
        self._k = 0
        self._fill = 0
        self.staged_bytes = 0
        self.transfers = 0
        self.stage_recorder = LatencyRecorder(f"w{worker_id}/pallas_stage")
        # Phase accounting, DevicePutStager parity (gap breakdown).
        self.transfer_wait_ns = 0
        self.put_submit_ns = 0
        self.checksum_reduce_ns = 0
        self._host_sum = 0
        # The per-slot device checksums accumulate ON DEVICE via a jitted
        # running add: an int(csum) per drain would be a host readback —
        # a full transfer-path round trip per slot (measured ~0.12 s on a
        # tunneled device, dwarfing the 8 MB landing pass itself) — and
        # an eager stack+sum at finish dispatches even worse (6.2 s
        # measured). The jitted add dispatches async per drain; finish
        # pays ONE readback.
        self._dev_acc: Optional[jax.Array] = None

    def _drain(self, k: int) -> None:
        item = self._inflight[k]
        if item is None:
            return
        landed, csum, submit_ns, n = item
        t0 = time.perf_counter_ns()
        landed.block_until_ready()
        self.transfer_wait_ns += time.perf_counter_ns() - t0
        self.stage_recorder.record_ns(time.perf_counter_ns() - submit_ns)
        # The landing pass read its input (which may alias the host slot
        # on zero-copy backends); with it complete the slot is reusable.
        self._dev_acc = (
            csum if self._dev_acc is None else _add_u32(self._dev_acc, csum)
        )
        self.staged_bytes += n
        self._inflight[k] = None

    def _free_view(self) -> memoryview:
        k = self._k
        self._drain(k)  # backpressure: previous landing of THIS slot
        return memoryview(self._slots[k].reshape(-1))[self._fill :]

    def _launch(self) -> None:
        k = self._k
        slot = self._slots[k]
        flat = slot.reshape(-1)
        n = self._fill
        if n < self._slot_bytes:
            flat[n:] = 0
        # Host-side sum BEFORE rotation: the slot still holds the payload
        # (the device_put may alias it; the drain gate protects reuse).
        self._host_sum = (
            self._host_sum + int(flat[:n].sum(dtype=np.uint64))
        ) % (1 << 32)
        t0 = time.perf_counter_ns()
        staged = jax.device_put(slot, self.device)
        landed, csum = pallas_land(staged)
        self.put_submit_ns += time.perf_counter_ns() - t0
        self._inflight[k] = (landed, csum, t0, n)
        self.transfers += 1
        self._fill = 0
        self._k = (k + 1) % self.depth
        if self.depth == 1:
            self._drain(k)

    def finish(self) -> dict:
        self.flush()
        for k in range(self.depth):
            self._drain(k)
        self._slots = []
        # ONE readback for the whole run (the accumulator already summed
        # on device). Timed separately: a stall here (device queue,
        # compile) would otherwise show up only as unexplained wall.
        t0 = time.perf_counter_ns()
        dev_sum = int(self._dev_acc) if self._dev_acc is not None else 0
        self.checksum_reduce_ns = time.perf_counter_ns() - t0
        self._dev_acc = None
        self._dev_sum = dev_sum % (1 << 32)
        return {
            "staged_bytes": self.staged_bytes,
            "transfers": self.transfers,
            "slot_bytes": self._slot_bytes,
            "n_chips": self.n_chips,
            "depth": self.depth,
            "stage_recorder": self.stage_recorder,
            "device": str(self.device),
            "transfer_wait_ns": self.transfer_wait_ns,
            "put_submit_ns": self.put_submit_ns,
            "checksum_reduce_ns": self.checksum_reduce_ns,
            "checksum_ok": self._dev_sum == self._host_sum,
            "checksum_device": self._dev_sum,
            "checksum_host": self._host_sum,
        }
