"""Pallas landing kernels — the on-device half of the staging path.

``device_put`` moves granule bytes host→HBM; these kernels are the HBM-side
landing ops, written Pallas-TPU-first:

* :func:`pallas_checksum` — mod-2³² byte-sum reduction of a landed granule,
  tiled (block, 128) through VMEM with an SMEM scalar accumulator.
* :func:`pallas_land` — fused copy+checksum: streams the staged granule
  HBM→VMEM→HBM into the landing buffer while accumulating the checksum.
  The grid pipeline gives the HBM↔VMEM double-buffering for free (the
  idiomatic TPU form of the hand-rolled DMA pattern), so validation costs
  one extra HBM round-trip, not a host readback.

On non-TPU backends (CPU tests) the kernels run in interpret mode; on TPU
they compile via Mosaic. Granules are (rows, 128) uint8 with rows a
multiple of the block size — guaranteed by the stager's lane-aligned slots.
"""

from __future__ import annotations

import functools
import time
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpubench.config import StagingConfig
from tpubench.metrics.recorder import LatencyRecorder
from tpubench.staging.device import GranuleAggregator

LANE = 128
# uint8 min tile is (32, 128); 512 rows = 64 KB/block in VMEM.
BLOCK_ROWS = 512


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _checksum_kernel(x_ref, out_ref):
    # Mosaic has no unsigned reductions; int32 two's-complement wraparound is
    # exactly mod-2^32 arithmetic, so accumulate signed and bitcast outside.
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_ref[0, 0] = jnp.int32(0)

    out_ref[0, 0] += jnp.sum(x_ref[:].astype(jnp.int32))


@functools.partial(jax.jit, static_argnames=("block_rows",))
def pallas_checksum(x: jax.Array, block_rows: int = BLOCK_ROWS) -> jax.Array:
    rows, lane = x.shape
    assert lane == LANE and rows % block_rows == 0, (rows, lane)
    out = pl.pallas_call(
        _checksum_kernel,
        grid=(rows // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, LANE), lambda i: (i, 0), memory_space=pltpu.VMEM)
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
        out_shape=jax.ShapeDtypeStruct((1, 1), jnp.int32),
        interpret=_interpret(),
    )(x)
    return jax.lax.bitcast_convert_type(out[0, 0], jnp.uint32)


def _land_kernel(x_ref, out_ref, csum_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        csum_ref[0, 0] = jnp.int32(0)

    blk = x_ref[:]
    out_ref[:] = blk
    csum_ref[0, 0] += jnp.sum(blk.astype(jnp.int32))


@functools.partial(jax.jit, static_argnames=("block_rows",))
def pallas_land(x: jax.Array, block_rows: int = BLOCK_ROWS):
    """(landed_copy, checksum) — one pipelined pass over the granule."""
    rows, lane = x.shape
    assert lane == LANE and rows % block_rows == 0, (rows, lane)
    landed, csum = pl.pallas_call(
        _land_kernel,
        grid=(rows // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, LANE), lambda i: (i, 0), memory_space=pltpu.VMEM)
        ],
        out_specs=[
            pl.BlockSpec((block_rows, LANE), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, LANE), jnp.uint8),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
        ],
        interpret=_interpret(),
    )(x)
    return landed, jax.lax.bitcast_convert_type(csum[0, 0], jnp.uint32)


class PallasStager(GranuleAggregator):
    """Staging sink: slot → device_put → fused pallas land (copy+checksum).

    Same sink contract as DevicePutStager — granules aggregate into
    ``slot_bytes`` slots (one transfer + one landing pass per slot),
    ``acquire`` guarantees granule-sized free space — but synchronous
    single-slot, and always validates (the checksum is free inside the
    landing pass).
    """

    def __init__(
        self,
        worker_id: int,
        granule_bytes: int,
        cfg: Optional[StagingConfig] = None,
        device=None,
        slot_bytes: Optional[int] = None,
    ):
        cfg = cfg or StagingConfig()
        devices = jax.local_devices()
        self.device = device if device is not None else devices[worker_id % len(devices)]
        self.n_chips = len(devices)
        lane = cfg.lane
        assert lane == LANE, "pallas path is lane-128 only"
        self._granule = granule_bytes
        # Round the aggregation target up so rows divide the kernel block.
        if slot_bytes is None:
            slot_bytes = cfg.slot_bytes
        slot_bytes = max(slot_bytes, granule_bytes)
        block_bytes = BLOCK_ROWS * LANE
        self._slot_bytes = -(-slot_bytes // block_bytes) * block_bytes
        self._shape = (self._slot_bytes // LANE, LANE)
        self._slot = np.zeros(self._shape, dtype=np.uint8)
        self._fill = 0
        self.staged_bytes = 0
        self.transfers = 0
        self.stage_recorder = LatencyRecorder(f"w{worker_id}/pallas_stage")
        self._host_sum = 0
        self._dev_sum = 0

    def _free_view(self) -> memoryview:
        """The single slot is synchronous — by the time the aggregator asks
        again, the previous landing pass has completed."""
        return memoryview(self._slot.reshape(-1))[self._fill :]

    def _launch(self) -> None:
        flat = self._slot.reshape(-1)
        n = self._fill
        if n < self._slot_bytes:
            flat[n:] = 0
        t0 = time.perf_counter_ns()
        staged = jax.device_put(self._slot, self.device)
        landed, csum = pallas_land(staged)
        landed.block_until_ready()
        self.stage_recorder.record_ns(time.perf_counter_ns() - t0)
        self._dev_sum = (self._dev_sum + int(csum)) % (1 << 32)
        self._host_sum = (
            self._host_sum + int(flat[:n].astype(np.uint32).sum())
        ) % (1 << 32)
        self.staged_bytes += n
        self.transfers += 1
        self._fill = 0

    def finish(self) -> dict:
        self.flush()
        return {
            "staged_bytes": self.staged_bytes,
            "transfers": self.transfers,
            "slot_bytes": self._slot_bytes,
            "n_chips": self.n_chips,
            "stage_recorder": self.stage_recorder,
            "device": str(self.device),
            "checksum_ok": self._dev_sum == self._host_sum,
            "checksum_device": self._dev_sum,
            "checksum_host": self._host_sum,
        }
