"""Jax-free staging stats assembly + rendering.

Separated from :mod:`tpubench.staging.device` (which imports jax at
module level) so the offline ``tpubench report`` path can render the
``extra["staging"]`` overlap block without bringing up a device runtime.
"""

from __future__ import annotations

from typing import Optional


def staging_efficiency(
    wait_ns: float, put_submit_ns: float, flight_ns: float, overlapped: bool
) -> Optional[float]:
    """Fraction of transfer flight time HIDDEN from the fetch thread:
    a serial pipeline waits out every transfer (→ ~0.0), a fully
    overlapped one never blocks (→ 1.0). Serial submits run ON the
    fetch thread (and on some runtimes the whole transfer happens
    inside the submission call), so the serial numerator counts
    put_submit too; the overlap reaper's submit time is concurrent
    with fetch and excluded. Single-sourced here — the window, the
    per-stager finish() stats, and the pooled extra["staging"] block
    must never disagree on the definition."""
    if flight_ns <= 0:
        return None
    blocked = wait_ns if overlapped else wait_ns + put_submit_ns
    return max(0.0, min(1.0, 1.0 - blocked / flight_ns))


def staging_extra(stats_list: list) -> Optional[dict]:
    """``extra["staging"]`` block from per-worker stager finish() stats:
    the overlap story — depth, transfers-in-flight gauge (p50/max),
    transfer wait vs flight time, and the pooled staging_efficiency
    (fraction of transfer flight time hidden from the fetch threads).
    Time fields are per-worker averages (staging_breakdown convention);
    byte/count fields are totals. None when no stager reported."""
    live = [st for st in stats_list if st and "transfer_flight_ns" in st]
    if not live:
        return None
    k = len(live)
    wait = sum(st.get("transfer_wait_ns", 0) for st in live)
    put = sum(st.get("put_submit_ns", 0) for st in live)
    flight = sum(st.get("transfer_flight_ns", 0) for st in live)
    overlap = live[0].get("drain") == "overlap"
    eff = staging_efficiency(wait, put, flight, overlap)
    return {
        "workers": k,
        "depth": max(st.get("depth", 1) for st in live),
        "drain": live[0].get("drain", "inline"),
        "transfers": sum(st.get("transfers", 0) for st in live),
        "staged_bytes": sum(st.get("staged_bytes", 0) for st in live),
        "transfer_wait_s": round(wait / 1e9 / k, 6),
        "submit_s": round(put / 1e9 / k, 6),
        "transfer_flight_s": round(flight / 1e9 / k, 6),
        "transfer_inflight": {
            "p50": round(
                sum(st.get("inflight_p50", 0.0) for st in live) / k, 2
            ),
            "max": max(st.get("inflight_max", 0) for st in live),
        },
        "out_of_order_completions": sum(
            st.get("out_of_order_completions", 0) for st in live
        ),
        "staging_efficiency": round(eff, 4) if eff is not None else None,
    }


def format_staging_block(d: dict) -> str:
    """One-line human rendering of ``extra["staging"]`` (printed by the
    CLI next to the scorecard and by ``tpubench report``)."""
    eff = d.get("staging_efficiency")
    infl = d.get("transfer_inflight") or {}
    return (
        f"  staging: drain={d.get('drain', '?')} depth={d.get('depth', '?')} "
        f"transfers={d.get('transfers', 0)} "
        f"inflight p50={infl.get('p50', 0)}/max={infl.get('max', 0)} "
        f"ooo={d.get('out_of_order_completions', 0)}  "
        f"transfer_wait={d.get('transfer_wait_s', 0.0):.3f}s "
        f"flight={d.get('transfer_flight_s', 0.0):.3f}s "
        f"efficiency={f'{eff:.1%}' if eff is not None else 'n/a'}"
    )
