"""Storage backends (L1/L0 of SURVEY §1).

``base`` defines the backend protocol; implementations:

* ``fake``      — in-process deterministic object store with fault injection
                  (SURVEY §5.3 prescription); the hermetic test target.
* ``fake_server`` — a real HTTP server speaking the GCS JSON surface, so the
                  http client path is exercised end-to-end without cloud.
* ``gcs_http``  — HTTP/1.1 JSON-API client (reference ``main.go:62-104``).
* ``gcs_grpc``  — gRPC client (reference ``main.go:106-117``), gated.
* ``local_fs``  — O_DIRECT filesystem path (reference ``benchmark-script/``).
"""

from tpubench.storage.base import (  # noqa: F401
    ObjectMeta,
    ObjectReader,
    StorageBackend,
    StorageError,
    deterministic_bytes,
)
from tpubench.storage.fake import FakeBackend, FaultPlan  # noqa: F401
from tpubench.storage.retry import Backoff, retry_call  # noqa: F401
from tpubench.storage.retrying import RetryingBackend  # noqa: F401
from tpubench.storage.tail import (  # noqa: F401
    CircuitOpenError,
    StallError,
    collect_tail_stats,
    wrap_tail,
)


def open_backend(cfg, fault=None, tracer=None) -> StorageBackend:
    """Factory from a BenchConfig (reference: main.go:169-177 protocol switch,
    minus its ignored-error bug). Every backend is wrapped with the
    client-level retry policy (main.go:179-184). ``tracer`` gives the
    HTTP/gRPC clients library-internal request spans (OC-bridge analog,
    trace_exporter.go:49-52)."""
    proto = cfg.transport.protocol
    if proto == "fake":
        from tpubench.storage.fake import FakeBackend, FaultPlan

        if fault is None and getattr(cfg.transport, "fault", None) is not None:
            fc = cfg.transport.fault
            if fc.active:
                import dataclasses

                # FaultConfig and FaultPlan share fields by contract; build
                # by name so a new knob added to one side fails loudly here
                # instead of being silently dropped.
                fault = FaultPlan(**dataclasses.asdict(fc))
        inner = FakeBackend.prepopulated(
            prefix=cfg.workload.object_name_prefix,
            count=max(cfg.workload.workers, cfg.workload.threads),
            size=cfg.workload.object_size,
            fault=fault,
        )
    elif proto == "http":
        from tpubench.storage.gcs_http import GcsHttpBackend
        from tpubench.storage.reactor_backend import maybe_wrap_reactor_fetch

        inner = GcsHttpBackend(
            bucket=cfg.workload.bucket, transport=cfg.transport, tracer=tracer
        )
        # Native fetch executors route backend reads (the serve plane's
        # open_backend fetches, prefetcher warms, demand misses) through
        # the shared reactor pool; the wrapper is lazy, so workloads that
        # drive tb_pool_* themselves never spin a second pool.
        inner = maybe_wrap_reactor_fetch(inner, cfg)
    elif proto == "grpc":
        from tpubench.storage.gcs_grpc import GcsGrpcBackend

        inner = GcsGrpcBackend(
            bucket=cfg.workload.bucket, transport=cfg.transport, tracer=tracer
        )
    elif proto == "local":
        from tpubench.storage.local_fs import LocalFsBackend

        inner = LocalFsBackend(root=cfg.workload.dir)
    else:
        raise ValueError(f"unknown protocol {proto!r} (http|grpc|local|fake)")
    # Tail-tolerance layer (storage/tail.py): hedging/watchdog/breaker
    # wrap INSIDE the retry decorator, so a StallError or CircuitOpenError
    # rides the same resume/backoff machinery as a server 503.
    inner = wrap_tail(
        inner, getattr(cfg.transport, "tail", None),
        chunk_bytes=cfg.workload.granule_bytes,
    )
    if cfg.transport.retry.policy == "never":
        return inner
    return RetryingBackend(inner, cfg.transport.retry)
