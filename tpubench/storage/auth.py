"""Token sources (reference ``auth.go``).

Reference order: service-account key file if given
(``newTokenSourceFromPath``, auth.go:28-51), else Application Default
Credentials (``google.DefaultTokenSource``, auth.go:55-68), with the
full-control GCS scope (auth.go:60). Here: the same two sources via
``google.auth`` (gated — hermetic runs against the fake server need no
auth), exposed through one ``TokenSource`` protocol.
"""

from __future__ import annotations

import threading
import time
from typing import Optional, Protocol

GCS_SCOPE = "https://www.googleapis.com/auth/devstorage.full_control"  # auth.go:60


class TokenSource(Protocol):
    def token(self) -> Optional[str]:
        """Returns a bearer token, or None for unauthenticated transports."""
        ...


class AnonymousTokenSource:
    """For the fake server / local paths — no Authorization header."""

    def token(self) -> Optional[str]:
        return None


class GoogleTokenSource:
    """ADC or service-account-file source with refresh-ahead caching."""

    def __init__(self, key_file: str = ""):
        import google.auth  # gated import: only needed for real GCS

        if key_file:
            from google.oauth2 import service_account

            self._creds = service_account.Credentials.from_service_account_file(
                key_file, scopes=[GCS_SCOPE]
            )
        else:
            self._creds, _ = google.auth.default(scopes=[GCS_SCOPE])
        self._lock = threading.Lock()

    def token(self) -> Optional[str]:
        with self._lock:
            if not self._creds.valid:
                import google.auth.transport.requests

                self._creds.refresh(google.auth.transport.requests.Request())
            return self._creds.token


def make_token_source(key_file: str, endpoint: str) -> TokenSource:
    """Endpoint override to a non-Google server ⇒ anonymous (hermetic runs)."""
    if endpoint and "googleapis.com" not in endpoint:
        return AnonymousTokenSource()
    return GoogleTokenSource(key_file)


class StaticTokenSource:
    """Test helper."""

    def __init__(self, tok: str, ttl_s: float = 3600.0):
        self._tok = tok
        self._exp = time.monotonic() + ttl_s

    def token(self) -> Optional[str]:
        return self._tok if time.monotonic() < self._exp else None
