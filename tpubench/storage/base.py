"""Backend protocol and shared primitives.

The reference's L1 surface is ``*storage.Client`` with
``bucket.Object(name).NewReader(ctx)`` streamed through a reused 2 MB buffer
(``main.go:134-140``). The protocol here keeps that shape — a streaming
reader filled into a caller-owned buffer — because (a) it reproduces the
reference's copy-buffer semantics and (b) a caller-owned buffer is what the
host→HBM staging path needs (fill a pinned granule, DMA it, reuse it).
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass
from typing import Iterator, Optional, Protocol, runtime_checkable

import numpy as np


class StorageError(Exception):
    """Backend error; ``transient`` drives the retry policy (SURVEY §5.3)."""

    def __init__(self, msg: str, *, transient: bool = False, code: int = 0):
        super().__init__(msg)
        self.transient = transient
        self.code = code


@dataclass(frozen=True)
class ObjectMeta:
    name: str
    size: int
    generation: int = 0


def object_meta_dict(meta: "ObjectMeta") -> dict:
    """The GCS JSON `storage#object` rendering of a stat result — ONE
    definition shared by every fake server (h1.1, h2, native) so their
    metadata surfaces can't drift apart."""
    return {
        "kind": "storage#object",
        "name": meta.name,
        "size": str(meta.size),
        "generation": str(meta.generation),
    }


@runtime_checkable
class ObjectReader(Protocol):
    """Streaming reader for one object (or byte range).

    ``readinto`` fills as much of ``buf`` as available and returns the byte
    count (0 = EOF). Implementations set ``first_byte_ns`` to a
    ``time.perf_counter_ns`` stamp when the first payload byte arrives — the
    observability the reference lacks (its ``NewReader``+``CopyBuffer`` hides
    time-to-first-byte inside full-read latency, ``main.go:135-140``).

    Readers MAY additionally carry ``generation``: the served object's
    generation (GCS ``x-goog-generation``), when the transport surfaces
    it — the fake backend and the JSON-API HTTP client do. Consumers
    (the pipeline chunk cache's invalidation tests) must treat a missing
    attribute or ``None`` as *unknown*, never as *unchanged*.
    """

    first_byte_ns: Optional[int]

    def readinto(self, buf: memoryview) -> int: ...

    def close(self) -> None: ...


@runtime_checkable
class ObjectWriter(Protocol):
    """Resumable streaming writer for one object (the storage-lifecycle
    write path — GCS resumable-upload shape: session open → content-range
    parts → finalize).

    ``write`` appends one part and returns the server-acknowledged
    committed offset; ``offset`` is the committed offset the CLIENT
    currently believes; ``committed`` re-probes the server (the
    308-with-Range resume query) and resyncs ``offset`` — the primitive
    the mid-part resume path is built on. ``finalize`` completes the
    object and returns its metadata; implementations make it IDEMPOTENT
    server-side where the wire allows (a finalize retried after a lost
    response must not double-commit). ``abort`` abandons the session
    (best-effort; never raises)."""

    offset: int

    def write(self, data) -> int: ...

    def committed(self) -> int: ...

    def finalize(self) -> ObjectMeta: ...

    def abort(self) -> None: ...


@runtime_checkable
class StorageBackend(Protocol):
    """L1 backend. One instance may be shared by many workers (the reference
    shares one ``*storage.Client`` across all goroutines, ``main.go:200-203``),
    so implementations must be thread-safe.

    ``write`` is the one-shot media upload; ``open_write`` the resumable
    multi-part session (both honor ``if_generation_match`` where the
    store has generations: 0 = object must not exist, N = current
    generation must be N; mismatch is a non-transient ``StorageError``
    with ``code=412`` — the idempotent-retry correctness anchor).
    ``list`` accepts ``page_size`` where the wire paginates
    (``maxResults``/``pageToken``); in-process stores ignore it."""

    def open_read(
        self, name: str, start: int = 0, length: Optional[int] = None
    ) -> ObjectReader: ...

    def write(
        self, name: str, data: bytes,
        if_generation_match: Optional[int] = None,
    ) -> ObjectMeta: ...

    def open_write(
        self, name: str, if_generation_match: Optional[int] = None
    ) -> ObjectWriter: ...

    def list(self, prefix: str = "", page_size: int = 0) -> list[ObjectMeta]: ...

    def stat(self, name: str) -> ObjectMeta: ...

    def delete(self, name: str) -> None: ...

    def close(self) -> None: ...


# ------------------------------------------------------------ helpers -------


def deterministic_bytes(name: str, size: int) -> np.ndarray:
    """Content of a synthetic object, reproducible from its name alone.

    Any host (or test) can regenerate any byte range without coordination —
    this is what lets the multi-host reassembly tests assert the gathered pod
    array equals the concatenated object bytes (SURVEY §4) without shipping
    data around.
    """
    seed = zlib.crc32(name.encode()) & 0xFFFFFFFF
    rng = np.random.Generator(np.random.Philox(seed))
    return rng.integers(0, 256, size=size, dtype=np.uint8)


def read_object_through(
    reader: ObjectReader, granule: memoryview, sink=None
) -> tuple[int, Optional[int]]:
    """The hot-loop copy: stream ``reader`` through the reused ``granule``
    buffer (reference: ``io.CopyBuffer(io.Discard, rc, 2MB)``, main.go:140).

    ``sink(filled_memoryview)`` is called per filled granule — ``None``
    discards (reference behavior); the staging path passes the HBM enqueue.
    Closes the reader (reference closes ``rc`` per read, main.go:148).
    Returns (total_bytes, first_byte_ns).
    """
    total = 0
    try:
        while True:
            n = reader.readinto(granule)
            if n <= 0:
                break
            total += n
            if sink is not None:
                sink(granule[:n])
    finally:
        reader.close()
    return total, reader.first_byte_ns


def read_object_into_sink(
    reader: ObjectReader, sink, granule_bytes: int
) -> tuple[int, Optional[int]]:
    """Zero-copy variant of :func:`read_object_through`: each granule is read
    *directly into* the staging slot the sink hands out (``sink.acquire()``),
    then staged with ``sink.commit(n)`` — no intermediate granule buffer
    (SURVEY hard-part (a): socket → pinned slot → HBM with no Python-held
    copy). Semantics otherwise identical: streams to EOF, closes the reader,
    returns (total_bytes, first_byte_ns).
    """
    total = 0
    try:
        while True:
            dst = sink.acquire()
            n = reader.readinto(dst[:granule_bytes])
            if n <= 0:
                break
            total += n
            sink.commit(n)
    finally:
        reader.close()
    return total, reader.first_byte_ns


def iter_ranges(size: int, granule: int) -> Iterator[tuple[int, int]]:
    """(start, length) granule decomposition of a byte range."""
    off = 0
    while off < size:
        n = min(granule, size - off)
        yield off, n
        off += n
