"""In-process fake object store with deterministic content and fault injection.

SURVEY §4 prescribes this as the hermetic integration target (the reference
validates only against real GCS); §5.3 prescribes fault injection (error %,
latency) which the reference has nowhere.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass
from typing import Optional

import numpy as np

from tpubench.storage.base import (
    ObjectMeta,
    StorageError,
    deterministic_bytes,
)


@dataclass
class FaultPlan:
    """Deterministic fault injection for tests and resilience benchmarks."""

    error_rate: float = 0.0  # probability a read-open raises transient 503
    read_error_rate: float = 0.0  # probability a granule read raises mid-stream
    latency_s: float = 0.0  # fixed added latency per open (first byte)
    per_read_latency_s: float = 0.0  # added latency per granule read
    seed: int = 0

    def rng(self) -> random.Random:
        return random.Random(self.seed)


class _FakeReader:
    """Streams a (possibly range-limited) view of an in-memory object."""

    def __init__(self, data: memoryview, fault: FaultPlan, rng: random.Random):
        self._data = data
        self._pos = 0
        self._fault = fault
        self._rng = rng
        self.first_byte_ns: Optional[int] = None
        self._closed = False

    def readinto(self, buf: memoryview) -> int:
        if self._closed:
            raise StorageError("reader closed", transient=False)
        if self._pos >= len(self._data):
            return 0
        if self._fault.per_read_latency_s:
            time.sleep(self._fault.per_read_latency_s)
        if self._fault.read_error_rate and self._rng.random() < self._fault.read_error_rate:
            raise StorageError("injected mid-stream failure", transient=True, code=503)
        n = min(len(buf), len(self._data) - self._pos)
        buf[:n] = self._data[self._pos : self._pos + n]
        self._pos += n
        if self.first_byte_ns is None:
            self.first_byte_ns = time.perf_counter_ns()
        return n

    def close(self) -> None:
        self._closed = True


class FakeBackend:
    """Thread-safe in-memory store. Objects created explicitly via ``write``
    or lazily from :func:`deterministic_bytes` via ``prepopulated``."""

    def __init__(self, fault: Optional[FaultPlan] = None):
        self._objects: dict[str, np.ndarray] = {}
        self._generation: dict[str, int] = {}
        self._lock = threading.Lock()
        self.fault = fault or FaultPlan()
        self._rng = self.fault.rng()
        self._rng_lock = threading.Lock()
        # Observability for tests: how many opens/reads/faults happened.
        self.open_count = 0
        self.injected_errors = 0

    # ------------------------------------------------------------- setup --
    @classmethod
    def prepopulated(
        cls,
        prefix: str,
        count: int,
        size: int,
        fault: Optional[FaultPlan] = None,
    ) -> "FakeBackend":
        """Objects named ``<prefix><i>`` (reference naming: object of worker i
        is ``ObjectNamePrefix + strconv.Itoa(workerId)``, main.go:121)."""
        be = cls(fault=fault)
        for i in range(count):
            name = f"{prefix}{i}"
            be._objects[name] = deterministic_bytes(name, size)
            be._generation[name] = 1
        return be

    # ----------------------------------------------------------- backend --
    def open_read(self, name: str, start: int = 0, length: Optional[int] = None):
        with self._rng_lock:
            r = self._rng.random()
            reader_rng = random.Random(self._rng.getrandbits(64))
        if self.fault.latency_s:
            time.sleep(self.fault.latency_s)
        if self.fault.error_rate and r < self.fault.error_rate:
            self.injected_errors += 1
            raise StorageError("injected open failure", transient=True, code=503)
        with self._lock:
            obj = self._objects.get(name)
            self.open_count += 1
        if obj is None:
            raise StorageError(f"object not found: {name}", transient=False, code=404)
        end = len(obj) if length is None else min(start + length, len(obj))
        if start > len(obj):
            raise StorageError(
                f"range start {start} > size {len(obj)}", transient=False, code=416
            )
        return _FakeReader(memoryview(obj.data)[start:end], self.fault, reader_rng)

    def write(self, name: str, data: bytes) -> ObjectMeta:
        arr = np.frombuffer(bytes(data), dtype=np.uint8).copy()
        with self._lock:
            self._objects[name] = arr
            self._generation[name] = self._generation.get(name, 0) + 1
            return ObjectMeta(name, len(arr), self._generation[name])

    def list(self, prefix: str = "") -> list[ObjectMeta]:
        with self._lock:
            return sorted(
                (
                    ObjectMeta(n, len(o), self._generation.get(n, 1))
                    for n, o in self._objects.items()
                    if n.startswith(prefix)
                ),
                key=lambda m: m.name,
            )

    def stat(self, name: str) -> ObjectMeta:
        with self._lock:
            obj = self._objects.get(name)
            if obj is None:
                raise StorageError(f"object not found: {name}", transient=False, code=404)
            return ObjectMeta(name, len(obj), self._generation.get(name, 1))

    def delete(self, name: str) -> None:
        with self._lock:
            if name not in self._objects:
                raise StorageError(f"object not found: {name}", transient=False, code=404)
            del self._objects[name]

    def close(self) -> None:
        pass
