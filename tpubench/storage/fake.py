"""In-process fake object store with deterministic content and fault injection.

SURVEY §4 prescribes this as the hermetic integration target (the reference
validates only against real GCS); §5.3 prescribes fault injection (error %,
latency) which the reference has nowhere.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass
from typing import Iterable, Optional

import numpy as np

from tpubench.storage.base import (
    ObjectMeta,
    StorageError,
    deterministic_bytes,
)


@dataclass
class FaultPlan:
    """Deterministic fault injection for tests and resilience benchmarks.

    Field-compatible with :class:`tpubench.config.FaultConfig` by contract
    (``open_backend`` builds one from the other by name). Beyond the
    rate/latency knobs it carries the chaos plane: shaped faults (stall /
    slow-drip / truncation / connection reset, all triggered after a byte
    threshold) and a time-phased schedule — ``phases`` is a sequence of
    ``(t0, t1, plan)`` windows relative to :meth:`arm`'s epoch during
    which ``plan`` replaces the base fields. Consumers (the fake backend
    AND both fake servers) resolve the moment's effective plan via
    :meth:`at` per operation, so a phase turning on mid-run hits streams
    already in flight — exactly the shape a tail-tolerance layer must
    survive."""

    error_rate: float = 0.0  # probability a read-open raises transient 503
    read_error_rate: float = 0.0  # probability a granule read raises mid-stream
    latency_s: float = 0.0  # fixed added latency per open (first byte)
    per_read_latency_s: float = 0.0  # added latency per granule read
    seed: int = 0
    # Shaped faults (see FaultConfig for semantics): stall once after N
    # delivered bytes (stall_rate = P(this reader stalls); big stall_s =
    # blackhole), cap per-reader throughput, end the body early, or kill
    # the stream after N bytes.
    stall_after_bytes: int = 0
    stall_s: float = 0.0
    stall_rate: float = 1.0
    drip_bps: float = 0.0
    truncate_after_bytes: int = 0
    reset_after_bytes: int = 0
    # Upload-side faults (the ckpt-save chaos surface; see FaultConfig):
    # part-append 503s, one mid-upload stall per session, and the
    # truncate-then-reset shape — a part whose bytes are partially
    # committed before the connection dies (one-shot per session so a
    # resumed upload can make progress past it).
    upload_error_rate: float = 0.0
    upload_stall_s: float = 0.0
    upload_stall_rate: float = 1.0
    upload_reset_after_bytes: int = 0
    phases: tuple = ()  # ((t0, t1, FaultPlan | field-dict), ...)

    def __post_init__(self):
        self._epoch: Optional[float] = None
        self._clock = time.monotonic
        norm = []
        for t0, t1, plan in self.phases or ():
            if isinstance(plan, dict):
                # Phase dicts inherit the base seed unless they set one,
                # so a seeded timeline stays deterministic end to end.
                plan = FaultPlan(**{"seed": self.seed, **plan})
            norm.append((float(t0), float(t1), plan))
        self.phases = tuple(norm)

    def rng(self) -> random.Random:
        return random.Random(self.seed)

    def arm(self, clock=None) -> "FaultPlan":
        """Pin the schedule's epoch to *now* (chaos calls this right
        before the workload starts so phase windows line up with the
        scorecard's timeline). ``clock`` is injectable for tests."""
        if clock is not None:
            self._clock = clock
        self._epoch = self._clock()
        return self

    def at(self, now: Optional[float] = None) -> "FaultPlan":
        """The effective plan at ``now`` (default: the armed clock's
        current reading; auto-arms on first use). Plans without phases
        return themselves — the common case costs one tuple check."""
        if not self.phases:
            return self
        if self._epoch is None:
            self.arm()
        t = (self._clock() if now is None else now) - self._epoch
        for t0, t1, plan in self.phases:
            if t0 <= t < t1:
                return plan
        return self


class _FakeReader:
    """Streams a (possibly range-limited) view of an in-memory object.

    Holds the ROOT fault plan (not a phase snapshot) and resolves the
    effective plan per ``readinto``, so a scheduled fault phase switching
    on mid-stream shapes a read that is already in flight."""

    def __init__(self, data: memoryview, fault: FaultPlan, rng: random.Random,
                 generation: int = 0):
        self._data = data
        self._pos = 0
        self._fault = fault
        self._rng = rng
        self.first_byte_ns: Optional[int] = None
        # Generation of the object this stream serves (the GCS
        # `x-goog-generation` surface) — what the pipeline cache keys on,
        # so generation-change invalidation is testable hermetically.
        self.generation = generation
        self._closed = False
        self._delivered = 0
        self._stall_rolled = False

    def readinto(self, buf: memoryview) -> int:
        if self._closed:
            raise StorageError("reader closed", transient=False)
        if self._pos >= len(self._data):
            return 0
        plan = self._fault.at()
        if plan.per_read_latency_s:
            time.sleep(plan.per_read_latency_s)
        if plan.read_error_rate and self._rng.random() < plan.read_error_rate:
            raise StorageError("injected mid-stream failure", transient=True, code=503)
        if plan.reset_after_bytes and self._delivered >= plan.reset_after_bytes:
            # Abrupt stream death: the servers translate this into a
            # closed socket / RST_STREAM; direct users see the transient.
            raise StorageError(
                "injected connection reset", transient=True, code=104
            )
        if plan.truncate_after_bytes and self._delivered >= plan.truncate_after_bytes:
            return 0  # clean EOF short of the announced length
        if plan.stall_s > 0 and not self._stall_rolled and (
            self._delivered >= plan.stall_after_bytes
        ):
            # One roll per reader: either this stream is a straggler
            # (pause once for stall_s) or it never stalls — the
            # probabilistic-straggler shape hedged reads race against.
            self._stall_rolled = True
            if plan.stall_rate >= 1.0 or self._rng.random() < plan.stall_rate:
                time.sleep(plan.stall_s)
        n = min(len(buf), len(self._data) - self._pos)
        if plan.drip_bps > 0:
            # Slow-drip: cap the chunk so the pacing sleep stays fine-
            # grained (a whole-granule sleep would look like a stall).
            n = max(1, min(n, int(plan.drip_bps * 0.05)))
        buf[:n] = self._data[self._pos : self._pos + n]
        self._pos += n
        self._delivered += n
        if self.first_byte_ns is None:
            self.first_byte_ns = time.perf_counter_ns()
        if plan.drip_bps > 0:
            time.sleep(n / plan.drip_bps)
        return n

    def close(self) -> None:
        self._closed = True


class _UploadSession:
    """One resumable-upload session: an append-only buffer plus the
    committed watermark and one-shot fault state. The store's
    finalize is IDEMPOTENT (the result meta is cached on the session) so
    a finalize retried after a lost response never double-commits — the
    correctness anchor for ``ifGenerationMatch`` retries."""

    __slots__ = ("uid", "name", "if_generation_match", "buf", "final_meta",
                 "stall_rolled", "reset_done")

    def __init__(self, uid: str, name: str, if_generation_match):
        self.uid = uid
        self.name = name
        self.if_generation_match = if_generation_match
        self.buf = bytearray()
        self.final_meta: Optional[ObjectMeta] = None
        self.stall_rolled = False
        self.reset_done = False


class _FakeWriter:
    """ObjectWriter over the backend's in-process session store."""

    def __init__(self, backend: "FakeBackend", uid: str):
        self._backend = backend
        self._uid = uid
        self.offset = 0

    def write(self, data) -> int:
        self.offset = self._backend.upload_append(
            self._uid, self.offset, data
        )
        return self.offset

    def committed(self) -> int:
        self.offset = self._backend.upload_committed(self._uid)
        return self.offset

    def finalize(self) -> ObjectMeta:
        return self._backend.finalize_upload(self._uid, total=self.offset)

    def abort(self) -> None:
        self._backend.abort_upload(self._uid)


class FakeBackend:
    """Thread-safe in-memory store. Objects created explicitly via ``write``
    or lazily from :func:`deterministic_bytes` via ``prepopulated``.

    Also carries the resumable-upload SESSION STORE (begin/append/
    committed/finalize/abort) that both fake servers translate wire
    requests onto — one semantics definition (offsets, preconditions,
    idempotent finalize, upload-side faults) the h1.1 and h2 surfaces
    cannot drift apart on."""

    def __init__(self, fault: Optional[FaultPlan] = None):
        self._objects: dict[str, np.ndarray] = {}
        self._generation: dict[str, int] = {}
        self._lock = threading.Lock()
        self.fault = fault or FaultPlan()
        self._rng = self.fault.rng()
        self._rng_lock = threading.Lock()
        # Observability for tests: how many opens/reads/faults happened.
        self.open_count = 0
        self.injected_errors = 0
        # Resumable-upload sessions (upload_id -> _UploadSession).
        self._uploads: dict[str, _UploadSession] = {}
        self._upload_seq = 0
        self.upload_parts = 0  # committed part appends (tests)

    # ------------------------------------------------------------- setup --
    @classmethod
    def prepopulated(
        cls,
        prefix: str,
        count: int,
        size: int,
        fault: Optional[FaultPlan] = None,
    ) -> "FakeBackend":
        """Objects named ``<prefix><i>`` (reference naming: object of worker i
        is ``ObjectNamePrefix + strconv.Itoa(workerId)``, main.go:121)."""
        be = cls(fault=fault)
        for i in range(count):
            name = f"{prefix}{i}"
            be._objects[name] = deterministic_bytes(name, size)
            be._generation[name] = 1
        return be

    @classmethod
    def from_population(
        cls,
        objects: Iterable,
        fault: Optional[FaultPlan] = None,
    ) -> "FakeBackend":
        """A store rebuilt from an explicit population — ``(name, size,
        generation)`` triples or ObjectMeta — the replay-bundle path:
        contents regenerate from :func:`deterministic_bytes` (name+size
        fully determine the bytes, same as ``prepopulated``), and the
        recorded generations are preserved so replayed chunk keys stay
        identical to the original run's."""
        be = cls(fault=fault)
        for obj in objects:
            if isinstance(obj, ObjectMeta):
                name, size, gen = obj.name, obj.size, obj.generation
            else:
                name, size, gen = obj
            be._objects[name] = deterministic_bytes(name, int(size))
            be._generation[name] = int(gen) or 1
        return be

    # ----------------------------------------------------------- backend --
    def open_read(self, name: str, start: int = 0, length: Optional[int] = None):
        with self._rng_lock:
            r = self._rng.random()
            reader_rng = random.Random(self._rng.getrandbits(64))
        plan = self.fault.at()
        if plan.latency_s:
            time.sleep(plan.latency_s)
        if plan.error_rate and r < plan.error_rate:
            self.injected_errors += 1
            raise StorageError("injected open failure", transient=True, code=503)
        with self._lock:
            obj = self._objects.get(name)
            gen = self._generation.get(name, 1)
            self.open_count += 1
        if obj is None:
            raise StorageError(f"object not found: {name}", transient=False, code=404)
        end = len(obj) if length is None else min(start + length, len(obj))
        if start > len(obj):
            raise StorageError(
                f"range start {start} > size {len(obj)}", transient=False, code=416
            )
        return _FakeReader(
            memoryview(obj.data)[start:end], self.fault, reader_rng,
            generation=gen,
        )

    def _check_generation(self, name: str, want: Optional[int]) -> None:
        """Precondition check under self._lock: ``want`` = 0 means the
        object must not exist; N means the current generation must be N.
        Mismatch is the GCS 412 — non-transient, so an idempotent retry
        layer never hammers a lost precondition."""
        if want is None:
            return
        current = self._generation.get(name, 0)
        if current != want:
            raise StorageError(
                f"ifGenerationMatch={want} does not match current "
                f"generation {current} of {name!r}",
                transient=False, code=412,
            )

    def write(self, name: str, data: bytes,
              if_generation_match: Optional[int] = None) -> ObjectMeta:
        arr = np.frombuffer(bytes(data), dtype=np.uint8).copy()
        with self._lock:
            self._check_generation(name, if_generation_match)
            self._objects[name] = arr
            self._generation[name] = self._generation.get(name, 0) + 1
            return ObjectMeta(name, len(arr), self._generation[name])

    # -------------------------------------------------- resumable uploads --
    def open_write(self, name: str,
                   if_generation_match: Optional[int] = None) -> _FakeWriter:
        return _FakeWriter(self, self.begin_upload(name, if_generation_match))

    def begin_upload(self, name: str,
                     if_generation_match: Optional[int] = None) -> str:
        with self._lock:
            self._upload_seq += 1
            uid = f"upload-{self._upload_seq}"
            self._uploads[uid] = _UploadSession(uid, name, if_generation_match)
            return uid

    def _session(self, uid: str) -> _UploadSession:
        s = self._uploads.get(uid)
        if s is None:
            raise StorageError(
                f"unknown upload session {uid!r}", transient=False, code=404
            )
        return s

    def upload_committed(self, uid: str) -> int:
        with self._lock:
            return len(self._session(uid).buf)

    def upload_append(self, uid: str, offset: int, data) -> int:
        """Append one content-range part at ``offset``; returns the new
        committed offset. Offsets BEHIND the watermark are an idempotent
        resend (the already-committed prefix is skipped); offsets ahead
        of it are a client bug (400). Upload-side faults (503s, one
        mid-upload stall, the commit-a-prefix-then-reset shape) fire
        here so the in-process backend and both wire servers share one
        fault surface."""
        mv = memoryview(data).cast("B") if not isinstance(
            data, memoryview
        ) else data.cast("B")
        plan = self.fault.at()
        with self._lock:
            s = self._session(uid)
            if s.final_meta is not None:
                raise StorageError(
                    f"upload {uid} already finalized", transient=False,
                    code=400,
                )
            committed = len(s.buf)
            stall = 0.0
            if (plan.upload_stall_s > 0 and not s.stall_rolled):
                s.stall_rolled = True
                with self._rng_lock:
                    roll = self._rng.random()
                if plan.upload_stall_rate >= 1.0 or roll < plan.upload_stall_rate:
                    stall = plan.upload_stall_s
        if stall:
            time.sleep(stall)
        if plan.upload_error_rate:
            with self._rng_lock:
                r = self._rng.random()
            if r < plan.upload_error_rate:
                self.injected_errors += 1
                raise StorageError(
                    "injected upload part failure", transient=True, code=503
                )
        with self._lock:
            s = self._session(uid)
            committed = len(s.buf)
            if offset > committed:
                raise StorageError(
                    f"upload {uid}: part offset {offset} ahead of "
                    f"committed {committed}", transient=False, code=400,
                )
            part = mv[committed - offset:] if offset < committed else mv
            if len(part) == 0:
                return committed
            if (
                plan.upload_reset_after_bytes and not s.reset_done
                and committed + len(part) > plan.upload_reset_after_bytes
            ):
                # Truncate-then-reset: commit only the prefix up to the
                # threshold, then die — the partially-committed part a
                # resume must re-probe (308 Range) and finish. One-shot
                # per session so the resumed upload makes progress.
                s.reset_done = True
                keep = max(0, plan.upload_reset_after_bytes - committed)
                s.buf += part[:keep]
                self.injected_errors += 1
                raise StorageError(
                    "injected upload reset mid-part", transient=True,
                    code=104,
                )
            s.buf += part
            self.upload_parts += 1
            return len(s.buf)

    def finalize_upload(self, uid: str,
                        total: Optional[int] = None) -> ObjectMeta:
        """Complete the session (idempotent: a finalize retried after a
        lost response returns the cached meta). The ``ifGenerationMatch``
        precondition is checked HERE — at commit time, like GCS — and a
        mismatch is the non-transient 412."""
        with self._lock:
            s = self._session(uid)
            if s.final_meta is not None:
                return s.final_meta
            if total is not None and total != len(s.buf):
                raise StorageError(
                    f"upload {uid}: declared total {total} != committed "
                    f"{len(s.buf)}", transient=False, code=400,
                )
            self._check_generation(s.name, s.if_generation_match)
            arr = np.frombuffer(bytes(s.buf), dtype=np.uint8).copy() \
                if s.buf else np.empty(0, dtype=np.uint8)
            self._objects[s.name] = arr
            self._generation[s.name] = self._generation.get(s.name, 0) + 1
            s.final_meta = ObjectMeta(
                s.name, len(arr), self._generation[s.name]
            )
            s.buf = bytearray()  # the store owns the bytes now
            return s.final_meta

    def upload_status(self, uid: str):
        """(committed_bytes, final_meta_or_None) — the resume probe's
        view, and the idempotency check the servers make before
        replaying a part against a finalized session."""
        with self._lock:
            s = self._session(uid)
            return len(s.buf), s.final_meta

    def abort_upload(self, uid: str) -> None:
        with self._lock:
            self._uploads.pop(uid, None)

    def list(self, prefix: str = "", page_size: int = 0) -> list[ObjectMeta]:
        # page_size is a WIRE concept (maxResults/pageToken); the
        # in-process store has no pages — accepted for protocol parity,
        # served as one listing (the fake servers do the real slicing).
        with self._lock:
            return sorted(
                (
                    ObjectMeta(n, len(o), self._generation.get(n, 1))
                    for n, o in self._objects.items()
                    if n.startswith(prefix)
                ),
                key=lambda m: m.name,
            )

    def stat(self, name: str) -> ObjectMeta:
        with self._lock:
            obj = self._objects.get(name)
            if obj is None:
                raise StorageError(f"object not found: {name}", transient=False, code=404)
            return ObjectMeta(name, len(obj), self._generation.get(name, 1))

    def delete(self, name: str) -> None:
        with self._lock:
            if name not in self._objects:
                raise StorageError(f"object not found: {name}", transient=False, code=404)
            del self._objects[name]

    def close(self) -> None:
        pass
