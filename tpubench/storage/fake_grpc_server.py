"""In-process gRPC storage-v2 fake server.

The gRPC twin of :mod:`fake_server`: serves ``google.storage.v2.Storage``
methods (ReadObject streaming in ≤2 MiB chunks — the server behavior the
reference's 2 MB buffer was tuned to, main.go:123-125) from a
:class:`FakeBackend`, with the same fault injection. Handlers are registered
generically from the generated request/response types, so no gapic servicer
codegen is needed.
"""

from __future__ import annotations

import threading
from concurrent import futures
from typing import Optional

import grpc

from google.cloud._storage_v2 import types as s2

from tpubench.storage.base import StorageError
from tpubench.storage.fake import FakeBackend
from tpubench.storage.gcs_grpc import MAX_READ_CHUNK

_SVC = "google.storage.v2.Storage"


def _object_name(req_object: str) -> str:
    return req_object


def _abort_storage_error(context, e: StorageError):
    code = {
        404: grpc.StatusCode.NOT_FOUND,
        416: grpc.StatusCode.OUT_OF_RANGE,
        503: grpc.StatusCode.UNAVAILABLE,
    }.get(e.code, grpc.StatusCode.UNKNOWN)
    context.abort(code, str(e))


class _Handlers:
    def __init__(self, backend: FakeBackend):
        self.backend = backend

    # --------------------------------------------------- streaming read --
    def read_object(self, request, context):
        name = _object_name(request.object_)
        length = request.read_limit or None
        try:
            meta = self.backend.stat(name)
            reader = self.backend.open_read(
                name, start=request.read_offset, length=length
            )
        except StorageError as e:
            _abort_storage_error(context, e)
            return
        first = True
        buf = bytearray(MAX_READ_CHUNK)
        mv = memoryview(buf)
        while True:
            try:
                n = reader.readinto(mv)
            except StorageError as e:
                _abort_storage_error(context, e)
                return
            if n <= 0:
                break
            resp = s2.ReadObjectResponse(
                checksummed_data=s2.ChecksummedData(content=bytes(mv[:n]))
            )
            if first:
                resp.metadata = s2.Object(
                    name=meta.name,
                    size=meta.size,
                    generation=meta.generation,
                )
                first = False
            yield resp
        reader.close()

    # ------------------------------------------------------------ unary --
    def get_object(self, request, context):
        try:
            m = self.backend.stat(_object_name(request.object_))
        except StorageError as e:
            _abort_storage_error(context, e)
            return
        return s2.Object(name=m.name, size=m.size, generation=m.generation)

    def list_objects(self, request, context):
        items = self.backend.list(request.prefix)
        return s2.ListObjectsResponse(
            objects=[
                s2.Object(name=m.name, size=m.size, generation=m.generation)
                for m in items
            ]
        )

    def delete_object(self, request, context):
        try:
            self.backend.delete(_object_name(request.object_))
        except StorageError as e:
            _abort_storage_error(context, e)
            return
        return b""

    def write_object(self, request_iterator, context):
        name = None
        chunks = []
        for req in request_iterator:
            if req.write_object_spec and req.write_object_spec.resource.name:
                name = req.write_object_spec.resource.name
            if req.checksummed_data and req.checksummed_data.content:
                chunks.append(bytes(req.checksummed_data.content))
        if name is None:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, "missing spec")
            return
        data = b"".join(chunks)
        meta = self.backend.write(name, data)
        return s2.WriteObjectResponse(
            resource=s2.Object(name=meta.name, size=meta.size)
        )


def _service(backend: FakeBackend) -> grpc.GenericRpcHandler:
    h = _Handlers(backend)
    return grpc.method_handlers_generic_handler(
        _SVC,
        {
            "ReadObject": grpc.unary_stream_rpc_method_handler(
                h.read_object,
                request_deserializer=s2.ReadObjectRequest.deserialize,
                response_serializer=s2.ReadObjectResponse.serialize,
            ),
            "GetObject": grpc.unary_unary_rpc_method_handler(
                h.get_object,
                request_deserializer=s2.GetObjectRequest.deserialize,
                response_serializer=s2.Object.serialize,
            ),
            "ListObjects": grpc.unary_unary_rpc_method_handler(
                h.list_objects,
                request_deserializer=s2.ListObjectsRequest.deserialize,
                response_serializer=s2.ListObjectsResponse.serialize,
            ),
            "DeleteObject": grpc.unary_unary_rpc_method_handler(
                h.delete_object,
                request_deserializer=s2.DeleteObjectRequest.deserialize,
                response_serializer=lambda b: b if isinstance(b, bytes) else b"",
            ),
            "WriteObject": grpc.stream_unary_rpc_method_handler(
                h.write_object,
                request_deserializer=s2.WriteObjectRequest.deserialize,
                response_serializer=s2.WriteObjectResponse.serialize,
            ),
        },
    )


class FakeGcsGrpcServer:
    """Threaded fake storage-v2 server.

    ``endpoint`` is ``insecure://host:port`` (h2c) by default; ``tls=True``
    serves over TLS with an ephemeral self-signed certificate (grpcio
    negotiates ALPN h2) so TLS gRPC client paths — the secure channel and
    the engine's native h2 client — test hermetically; ``cafile`` then
    points at the PEM to trust.
    """

    def __init__(
        self,
        backend: Optional[FakeBackend] = None,
        port: int = 0,
        tls: bool = False,
    ):
        self.backend = backend or FakeBackend()
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=16),
            options=[("grpc.max_send_message_length", 16 * 1024 * 1024)],
        )
        self._server.add_generic_rpc_handlers((_service(self.backend),))
        self._tls = tls
        self.cafile = ""
        if tls:
            from tpubench.storage.fake_server import make_self_signed_cert

            self.cafile, keyfile = make_self_signed_cert()
            with open(keyfile, "rb") as f:
                key = f.read()
            with open(self.cafile, "rb") as f:
                cert = f.read()
            creds = grpc.ssl_server_credentials([(key, cert)])
            self._port = self._server.add_secure_port(f"127.0.0.1:{port}", creds)
        else:
            self._port = self._server.add_insecure_port(f"127.0.0.1:{port}")
        self._started = threading.Event()

    @property
    def endpoint(self) -> str:
        if self._tls:
            return f"127.0.0.1:{self._port}"  # no scheme = TLS (like real GCS)
        return f"insecure://127.0.0.1:{self._port}"

    def start(self) -> "FakeGcsGrpcServer":
        self._server.start()
        self._started.set()
        return self

    def stop(self) -> None:
        self._server.stop(grace=1).wait()

    def __enter__(self) -> "FakeGcsGrpcServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
