"""Hermetic storage-v2 gRPC server speaking raw HTTP/2 frames — no grpcio.

The wire twin of :class:`FakeGcsGrpcServer`: same ``endpoint`` shape
(``insecure://host:port`` h2c, ``host:port`` + ``cafile`` for TLS), same
constructor, same context-manager lifecycle — tests retarget by class
swap. It serves ReadObject / GetObject / ListObjects / DeleteObject /
StartResumableWrite / WriteObject / BidiWriteObject / QueryWriteStatus
from the SAME :class:`FakeBackend` instance the h1.1 and h2 fakes use,
so one FaultPlan epoch and one ``_UploadSession`` store govern every
transport in a run — a transport A/B under chaos compares transports,
not two independently-armed fault timelines.

Fault surfaces, kept aligned with the other fakes:

- read-plane open faults (latency, error_rate, 404/416) fire inside
  ``backend.open_read``/``stat`` and map to grpc-status trailers;
- mid-stream read faults from the backend reader map to trailers,
  EXCEPT the injected connection-reset shape (StorageError code 104)
  which kills the socket with an RST — the client must exercise its
  EOF path, exactly as against the h1.1 fake's mid-body close;
- upload faults (503 rolls, the one-shot stall, commit-a-prefix-then-
  reset) fire inside ``backend.upload_append`` — the stall manifests
  as a delayed bidi ack, the reset as a dead socket mid-stream.

Unlike :class:`fake_h2_server._Conn` (whose frame loop discards DATA —
it serves GETs), this loop routes DATA payloads into per-stream queues
so client-streaming and bidi methods consume messages incrementally.
"""

from __future__ import annotations

import queue
import socket
import ssl
import struct
import threading
import time
from typing import Optional

from tpubench.storage.base import StorageError
from tpubench.storage.fake import FakeBackend
from tpubench.storage.fake_h2_server import (
    _PREFACE,
    _HpackError,
    _hp_literal,
    decode_request_headers,
)
from tpubench.storage.grpc_wire import proto
from tpubench.storage.grpc_wire.framing import (
    OK,
    FrameDecoder,
    WireCodecError,
    encode_frame,
    storage_error_to_status,
)

_DATA, _HEADERS, _RST_STREAM, _SETTINGS, _PING, _GOAWAY = 0, 1, 3, 4, 6, 7
_UNIMPLEMENTED = 12

# Largest content per ReadObjectResponse — mirrors the library path's
# server (google.storage.v2 caps ChecksummedData at 2 MiB).
MAX_READ_CHUNK = 2 * 1024 * 1024


class _Kill(Exception):
    """Socket already aborted (injected reset); unwind silently."""


class _Stream:
    def __init__(self, stream_id: int, headers: dict):
        self.id = stream_id
        self.headers = headers
        self.q: "queue.Queue[Optional[bytes]]" = queue.Queue()
        self.cancelled = threading.Event()


class _Rsp:
    """Per-stream response side: lazy initial HEADERS, framed DATA
    messages, trailers (trailers-only when nothing was sent yet)."""

    def __init__(self, conn: "_GrpcConn", stream_id: int):
        self._conn = conn
        self._sid = stream_id
        self._opened = False
        self.done = False

    def msg(self, m: "proto.Msg") -> None:
        conn = self._conn
        if not self._opened:
            self._opened = True
            conn.send_frame(
                _HEADERS, 0x4, self._sid,
                _hp_literal(":status", "200")
                + _hp_literal("content-type", "application/grpc"),
            )
        framed = encode_frame(m.encode())
        mv = memoryview(framed)
        step = conn.client_max_frame
        for off in range(0, len(mv), step):
            conn.send_frame(_DATA, 0, self._sid, bytes(mv[off : off + step]))

    def trailers(self, status: int, message: str = "") -> None:
        if self.done:
            return
        self.done = True
        block = b""
        if not self._opened:
            # Trailers-only response (legal gRPC: one HEADERS frame).
            block += _hp_literal(":status", "200") + _hp_literal(
                "content-type", "application/grpc"
            )
        block += _hp_literal("grpc-status", str(status))
        if message:
            block += _hp_literal(
                "grpc-message", message.replace("\r", " ").replace("\n", " ")
            )
        self._conn.send_frame(_HEADERS, 0x4 | 0x1, self._sid, block)


class _GrpcConn:
    def __init__(self, sock: socket.socket, backend: FakeBackend):
        self.sock = sock
        self.backend = backend
        self.wlock = threading.Lock()
        self.client_max_frame = 16384
        self._streams: dict[int, _Stream] = {}

    # ---------------------------------------------------------- frame io --
    def _recv_all(self, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    def send_frame(self, ftype: int, flags: int, stream: int, payload: bytes):
        hdr = struct.pack("!I", len(payload))[1:] + bytes(
            [ftype, flags]
        ) + struct.pack("!I", stream & 0x7FFFFFFF)
        with self.wlock:
            self.sock.sendall(hdr + payload)

    def abort(self) -> None:
        """Abrupt RST-style kill: the injected-reset fault shape (code
        104) — the peer sees a reset mid-RPC, never trailers.

        Called from a stream-handler thread while the frame loop is
        blocked in ``recv`` on the same fd: that in-flight syscall holds
        the kernel socket open, so ``close()`` alone would defer the
        teardown (and the RST) until the peer's read deadline fires.
        ``shutdown(SHUT_RD)`` is purely local — it wakes the blocked
        reader without putting a FIN on the wire — so the last close
        drops the fd with ``SO_LINGER(1,0)`` armed and the peer sees a
        genuine reset immediately."""
        try:
            self.sock.setsockopt(
                socket.SOL_SOCKET, socket.SO_LINGER,
                struct.pack("ii", 1, 0),
            )
        except OSError:
            pass
        try:
            self.sock.shutdown(socket.SHUT_RD)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass

    # ------------------------------------------------------------ serving --
    def serve(self) -> None:
        try:
            first = self._recv_all(len(_PREFACE))
            if first != _PREFACE:
                return
            self.send_frame(_SETTINGS, 0, 0, b"")
            while True:
                fh = self._recv_all(9)
                if fh is None:
                    return
                flen = (fh[0] << 16) | (fh[1] << 8) | fh[2]
                ftype, fflags = fh[3], fh[4]
                stream = struct.unpack("!I", fh[5:9])[0] & 0x7FFFFFFF
                payload = self._recv_all(flen) if flen else b""
                if payload is None:
                    return
                if ftype == _SETTINGS and not fflags & 0x1:
                    for off in range(0, len(payload) - 5, 6):
                        ident, value = struct.unpack_from("!HI", payload, off)
                        if ident == 0x5:  # SETTINGS_MAX_FRAME_SIZE
                            self.client_max_frame = value
                    self.send_frame(_SETTINGS, 0x1, 0, b"")
                elif ftype == _PING and not fflags & 0x1:
                    self.send_frame(_PING, 0x1, 0, payload)
                elif ftype == _HEADERS:
                    if not fflags & 0x4:
                        return  # CONTINUATION unsupported: drop conn
                    block = payload
                    if fflags & 0x8:  # PADDED
                        pad = block[0]
                        block = block[1 : len(block) - pad]
                    if fflags & 0x20:  # PRIORITY
                        block = block[5:]
                    try:
                        hdrs = decode_request_headers(block)
                    except _HpackError:
                        continue
                    st = _Stream(stream, hdrs)
                    self._streams[stream] = st
                    if fflags & 0x1:
                        st.q.put(None)
                    threading.Thread(
                        target=self._dispatch, args=(st,),
                        name=f"grpc-wire-stream-{stream}", daemon=True,
                    ).start()
                elif ftype == _DATA:
                    st = self._streams.get(stream)
                    if st is not None:
                        if fflags & 0x8 and payload:  # PADDED
                            pad = payload[0]
                            payload = payload[1 : len(payload) - pad]
                        if payload:
                            st.q.put(payload)
                        if fflags & 0x1:
                            st.q.put(None)
                elif ftype == _RST_STREAM:
                    st = self._streams.pop(stream, None)
                    if st is not None:
                        st.cancelled.set()
                        st.q.put(None)
                elif ftype == _GOAWAY:
                    return
        except OSError:
            return
        finally:
            for st in self._streams.values():
                st.q.put(None)  # unblock any handler still reading
            try:
                self.sock.close()
            except OSError:
                pass

    # --------------------------------------------------------- dispatch --
    def _dispatch(self, st: _Stream) -> None:
        method = st.headers.get(":path", "").rsplit("/", 1)[-1]
        handler = getattr(self, f"_rpc_{method}", None)
        rsp = _Rsp(self, st.id)
        try:
            if handler is None:
                rsp.trailers(_UNIMPLEMENTED, f"unknown method {method!r}")
                return
            handler(st, rsp)
        except _Kill:
            return
        except StorageError as e:
            if getattr(e, "code", None) == 104:
                self.abort()
                return
            status, msg = storage_error_to_status(e)
            try:
                rsp.trailers(status, msg)
            except OSError:
                pass
        except OSError:
            pass
        except Exception as e:  # handler bug: surface as UNKNOWN, not a hang
            try:
                rsp.trailers(2, f"{type(e).__name__}: {e}")
            except OSError:
                pass

    # ------------------------------------------------------ message input --
    def _iter_msgs(self, st: _Stream):
        dec = FrameDecoder()
        while True:
            m = dec.next()
            if m is not None:
                yield m
                continue
            item = st.q.get()
            if item is None:
                dec.finish()  # partial frame at END_STREAM → WireCodecError
                return
            dec.feed(item)

    def _one_msg(self, st: _Stream) -> bytes:
        msgs = list(self._iter_msgs(st))
        if len(msgs) != 1:
            raise WireCodecError(
                f"unary call carried {len(msgs)} messages"
            )
        return msgs[0]

    def _gate(self) -> None:
        """Open-time fault gate for the metadata unaries, mirroring the
        h2 fake's handler gate (read-plane opens roll inside
        ``backend.open_read`` instead — one roll per op either way)."""
        be = self.backend
        fault = be.fault.at()
        if fault.latency_s:
            time.sleep(fault.latency_s)
        if fault.error_rate:
            with be._rng_lock:
                r = be._rng.random()
            if r < fault.error_rate:
                be.injected_errors += 1
                raise StorageError(
                    "injected unavailability", transient=True, code=503
                )

    @staticmethod
    def _obj(meta) -> proto.Object:
        return proto.Object(
            name=meta.name, generation=meta.generation, size=meta.size
        )

    # --------------------------------------------------------- read plane --
    def _rpc_ReadObject(self, st: _Stream, rsp: _Rsp) -> None:
        req = proto.ReadObjectRequest.decode(self._one_msg(st))
        be = self.backend
        meta = be.stat(req.object)
        start = req.read_offset
        length = req.read_limit or max(meta.size - start, 0)
        reader = be.open_read(req.object, start=start, length=length)
        try:
            sent_meta = False
            buf = bytearray(MAX_READ_CHUNK)
            mv = memoryview(buf)
            while True:
                if st.cancelled.is_set():
                    return
                n = reader.readinto(mv)
                if n <= 0:
                    break
                content = bytes(mv[:n])
                rsp.msg(
                    proto.ReadObjectResponse(
                        checksummed_data=proto.ChecksummedData(
                            content=content,
                            crc32c=proto.crc32c_of(content),
                        ),
                        metadata=None if sent_meta else self._obj(meta),
                    )
                )
                sent_meta = True
            if not sent_meta:
                # Empty body: metadata still rides the (only) response.
                rsp.msg(proto.ReadObjectResponse(metadata=self._obj(meta)))
            rsp.trailers(OK)
        finally:
            reader.close()

    def _rpc_GetObject(self, st: _Stream, rsp: _Rsp) -> None:
        req = proto.GetObjectRequest.decode(self._one_msg(st))
        self._gate()
        meta = self.backend.stat(req.object)
        rsp.msg(self._obj(meta))
        rsp.trailers(OK)

    def _rpc_ListObjects(self, st: _Stream, rsp: _Rsp) -> None:
        req = proto.ListObjectsRequest.decode(self._one_msg(st))
        self._gate()
        metas = self.backend.list(req.prefix)
        start = int(req.page_token) if req.page_token else 0
        if req.page_size:
            page = metas[start : start + req.page_size]
        else:
            page = metas[start:]
        nxt = ""
        if req.page_size and start + len(page) < len(metas):
            nxt = str(start + len(page))
        rsp.msg(
            proto.ListObjectsResponse(
                objects=[self._obj(m) for m in page], next_page_token=nxt
            )
        )
        rsp.trailers(OK)

    def _rpc_DeleteObject(self, st: _Stream, rsp: _Rsp) -> None:
        req = proto.DeleteObjectRequest.decode(self._one_msg(st))
        self._gate()
        self.backend.delete(req.object)
        rsp.msg(proto.Msg())  # google.protobuf.Empty
        rsp.trailers(OK)

    # -------------------------------------------------------- write plane --
    def _rpc_StartResumableWrite(self, st: _Stream, rsp: _Rsp) -> None:
        req = proto.StartResumableWriteRequest.decode(self._one_msg(st))
        spec = req.write_object_spec
        if spec is None or spec.resource is None or not spec.resource.name:
            raise WireCodecError("StartResumableWrite without object name")
        uid = self.backend.begin_upload(
            spec.resource.name, if_generation_match=spec.if_generation_match
        )
        rsp.msg(proto.StartResumableWriteResponse(upload_id=uid))
        rsp.trailers(OK)

    def _rpc_QueryWriteStatus(self, st: _Stream, rsp: _Rsp) -> None:
        req = proto.QueryWriteStatusRequest.decode(self._one_msg(st))
        committed, final = self.backend.upload_status(req.upload_id)
        if final is not None:
            rsp.msg(
                proto.QueryWriteStatusResponse(
                    persisted_size=final.size, resource=self._obj(final)
                )
            )
        else:
            rsp.msg(proto.QueryWriteStatusResponse(persisted_size=committed))
        rsp.trailers(OK)

    def _bidi_begin(self, msg) -> str:
        if msg.upload_id:
            return msg.upload_id
        spec = msg.write_object_spec
        if spec is not None and spec.resource is not None and spec.resource.name:
            return self.backend.begin_upload(
                spec.resource.name,
                if_generation_match=spec.if_generation_match,
            )
        raise WireCodecError(
            "first write message needs upload_id or write_object_spec"
        )

    def _append(self, uid: str, msg) -> int:
        """One chunk through the shared fault point; code-104 resets
        kill the socket (the client sees a dead conn, not trailers)."""
        cd = msg.checksummed_data
        if cd is None or not cd.content:
            return self.backend.upload_committed(uid)
        try:
            return self.backend.upload_append(uid, msg.write_offset, cd.content)
        except StorageError as e:
            if getattr(e, "code", None) == 104:
                self.abort()
                raise _Kill() from e
            raise

    def _rpc_WriteObject(self, st: _Stream, rsp: _Rsp) -> None:
        uid: Optional[str] = None
        committed = 0
        for raw in self._iter_msgs(st):
            msg = proto.WriteObjectRequest.decode(raw)
            if uid is None:
                uid = self._bidi_begin(msg)
            committed = self._append(uid, msg)
            if msg.finish_write:
                cd = msg.checksummed_data
                total = msg.write_offset + (len(cd.content) if cd else 0)
                meta = self.backend.finalize_upload(uid, total=total)
                rsp.msg(
                    proto.WriteObjectResponse(
                        persisted_size=meta.size, resource=self._obj(meta)
                    )
                )
                rsp.trailers(OK)
                return
        if uid is None:
            raise WireCodecError("WriteObject stream carried no messages")
        # Half-close without finish_write: report progress; the session
        # stays open for QueryWriteStatus / a resumed stream.
        rsp.msg(proto.WriteObjectResponse(persisted_size=committed))
        rsp.trailers(OK)

    def _rpc_BidiWriteObject(self, st: _Stream, rsp: _Rsp) -> None:
        uid: Optional[str] = None
        for raw in self._iter_msgs(st):
            msg = proto.BidiWriteObjectRequest.decode(raw)
            if uid is None:
                uid = self._bidi_begin(msg)
            committed = self._append(uid, msg)
            if msg.finish_write:
                cd = msg.checksummed_data
                if cd is not None and cd.content:
                    total = msg.write_offset + len(cd.content)
                else:
                    total = msg.write_offset or None
                meta = self.backend.finalize_upload(uid, total=total)
                rsp.msg(
                    proto.BidiWriteObjectResponse(
                        persisted_size=meta.size, resource=self._obj(meta)
                    )
                )
                rsp.trailers(OK)
                return
            if msg.state_lookup:
                rsp.msg(proto.BidiWriteObjectResponse(persisted_size=committed))
        # Input ended without finish_write (client broke away to
        # re-probe): close our side cleanly, session stays resumable.
        rsp.trailers(OK)


class FakeGrpcWireServer:
    """Threaded hermetic storage-v2 gRPC server (raw frames, no grpcio).

    Same surface as :class:`FakeGcsGrpcServer`: ``endpoint`` is
    ``insecure://host:port`` (h2c) by default; ``tls=True`` serves TLS
    with an ephemeral self-signed cert and ALPN h2, ``cafile`` pointing
    at the PEM to trust.
    """

    def __init__(
        self,
        backend: Optional[FakeBackend] = None,
        port: int = 0,
        tls: bool = False,
    ):
        self.backend = backend or FakeBackend()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("127.0.0.1", port))
        self._sock.listen(16)
        self._port = self._sock.getsockname()[1]
        self._tls = tls
        self.cafile = ""
        self._ctx = None
        if tls:
            from tpubench.storage.fake_server import make_self_signed_cert

            self.cafile, keyfile = make_self_signed_cert()
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(self.cafile, keyfile)
            ctx.set_alpn_protocols(["h2"])
            self._ctx = ctx
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def endpoint(self) -> str:
        if self._tls:
            return f"127.0.0.1:{self._port}"  # no scheme = TLS (like real GCS)
        return f"insecure://127.0.0.1:{self._port}"

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            if self._ctx is not None:
                try:
                    conn = self._ctx.wrap_socket(conn, server_side=True)
                except (ssl.SSLError, OSError):
                    continue
            threading.Thread(
                target=_GrpcConn(conn, self.backend).serve,
                name="grpc-wire-conn", daemon=True,
            ).start()

    def start(self) -> "FakeGrpcWireServer":
        self._thread = threading.Thread(
            target=self._accept_loop, name="grpc-wire-accept", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        if self._thread:
            self._thread.join(timeout=5)

    def __enter__(self) -> "FakeGrpcWireServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
