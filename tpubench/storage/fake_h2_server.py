"""A minimal HTTP/2 fake-GCS media server (h2c prior knowledge / TLS+ALPN).

The h2 twin of :mod:`fake_server`'s HTTP/1.1 server, backing hermetic tests
for the native HTTP/2 client (the reference's ``ForceAttemptHTTP2`` branch,
``main.go:76-80``). Python's stdlib has no h2 server and the image ships no
``h2`` package, so this implements exactly the slice the tests need:

* connection preface + SETTINGS exchange, PING replies;
* request HEADERS decoding via structural HPACK (indexed entries resolved
  against the static table for the pseudo-headers clients commonly index;
  literal entries with plain or static-table names). Huffman-coded request
  strings are answered with a 400 — the in-repo native client never
  huffman-encodes (engine.cc hp_header), and scoping the fake to its
  traffic keeps this server small and predictable;
* ``GET .../o/<object>?alt=media`` with ``Range`` support, served as a
  literal ``:status`` + ``content-length`` HEADERS frame and 16 KB DATA
  frames from the backing :class:`FakeBackend` (fault injection included);
* concurrent streams: requests are served as their END_STREAM arrives;
  responses for different streams interleave legally.

Flow control: the server respects nothing fancier than the client's
initial window (the native client advertises 2^31-1, so writes never
stall in practice for test-sized objects).
"""

from __future__ import annotations

import socket
import ssl
import struct
import threading
import urllib.parse
from typing import Optional

from tpubench.storage.base import StorageError
from tpubench.storage.fake import FakeBackend

_PREFACE = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"

# RFC 7541 Appendix A static-table entries this server resolves (the ones
# clients commonly send indexed for a GET).
_STATIC = {
    2: (":method", "GET"),
    3: (":method", "POST"),
    4: (":path", "/"),
    6: (":scheme", "http"),
    7: (":scheme", "https"),
}


class _HpackError(Exception):
    pass


def _hpd_int(data: bytes, i: int, prefix: int) -> tuple[int, int]:
    if i >= len(data):
        raise _HpackError("truncated int")
    maxp = (1 << prefix) - 1
    v = data[i] & maxp
    i += 1
    if v == maxp:
        m = 0
        while True:
            if i >= len(data) or m > 56:
                raise _HpackError("truncated varint")
            b = data[i]
            i += 1
            v += (b & 0x7F) << m
            if not b & 0x80:
                break
            m += 7
    return v, i


def _hpd_str(data: bytes, i: int) -> tuple[str, int]:
    if i >= len(data):
        raise _HpackError("truncated string")
    huff = data[i] & 0x80
    n, i = _hpd_int(data, i, 7)
    if i + n > len(data):
        raise _HpackError("string past end")
    if huff:
        # Scoped out (see module docstring): reject rather than misparse.
        raise _HpackError("huffman-coded request strings unsupported")
    s = data[i : i + n].decode("latin-1")
    return s, i + n


def decode_request_headers(block: bytes) -> dict[str, str]:
    """Structural HPACK decode of a request header block into a dict."""
    out: dict[str, str] = {}
    i = 0
    while i < len(block):
        b = block[i]
        if b & 0x80:  # indexed
            idx, i = _hpd_int(block, i, 7)
            if idx in _STATIC:
                k, v = _STATIC[idx]
                out[k] = v
            continue
        if (b & 0xE0) == 0x20:  # dynamic table size update
            _, i = _hpd_int(block, i, 5)
            continue
        prefix = 6 if b & 0x40 else 4
        idx, i = _hpd_int(block, i, prefix)
        if idx == 0:
            name, i = _hpd_str(block, i)
        else:
            name = _STATIC.get(idx, (f"idx{idx}", ""))[0]
        value, i = _hpd_str(block, i)
        out[name.lower()] = value
    return out


def _hp_literal(name: str, value: str) -> bytes:
    def _s(x: bytes) -> bytes:
        if len(x) < 127:
            return bytes([len(x)]) + x
        n = len(x) - 127
        out = bytearray([127])
        while n >= 128:
            out.append((n & 0x7F) | 0x80)
            n >>= 7
        out.append(n)
        return bytes(out) + x

    return b"\x10" + _s(name.encode()) + _s(value.encode())


class _Conn:
    def __init__(
        self,
        sock: socket.socket,
        backend: FakeBackend,
        truncate_body_bytes: Optional[int] = None,
        send_interim_1xx: bool = False,
        interim_end_stream: bool = False,
    ):
        self.sock = sock
        self.backend = backend
        # Fault knob: cleanly END_STREAM media bodies after this many
        # bytes, SHORT of the announced content-length — the
        # proxy-died-mid-stream shape a correct client must reject
        # (distinct from RST_STREAM: the stream "succeeds" on the wire).
        self.truncate_body_bytes = truncate_body_bytes
        # Knob: precede every response with an informational `:status 103`
        # HEADERS block (RFC 9113 §8.1) — a client that mistakes it for
        # the response discards the real block's content-length and its
        # truncation check goes blind.
        self.send_interim_1xx = send_interim_1xx
        # Knob: MALFORMED interim — the 103 block carries END_STREAM
        # (forbidden by RFC 9113 §8.1). A correct client fails the stream
        # as a protocol error; a sloppy one "finishes" it with the
        # truncation check never armed.
        self.interim_end_stream = interim_end_stream
        self.wlock = threading.Lock()

    # ---------------------------------------------------------- frame io --
    def _recv_all(self, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    def send_frame(self, ftype: int, flags: int, stream: int, payload: bytes):
        hdr = struct.pack("!I", len(payload))[1:] + bytes(
            [ftype, flags]
        ) + struct.pack("!I", stream & 0x7FFFFFFF)
        with self.wlock:
            self.sock.sendall(hdr + payload)

    # ------------------------------------------------------------ serving --
    def serve(self) -> None:
        try:
            first = self._recv_all(len(_PREFACE))
            if first is None:
                return
            if first != _PREFACE:
                # Not the h2 preface: an HTTP/1.1 request (real GCS serves
                # both protocols on one port; metadata requests from an
                # http2=True client ride h1.1). Serve it minimally.
                return self._serve_h11(first)
            self.send_frame(4, 0, 0, b"")  # empty SETTINGS
            headers_by_stream: dict[int, dict] = {}
            while True:
                fh = self._recv_all(9)
                if fh is None:
                    return
                flen = (fh[0] << 16) | (fh[1] << 8) | fh[2]
                ftype, fflags = fh[3], fh[4]
                stream = struct.unpack("!I", fh[5:9])[0] & 0x7FFFFFFF
                payload = self._recv_all(flen) if flen else b""
                if payload is None:
                    return
                if ftype == 4 and not fflags & 0x1:  # SETTINGS -> ACK
                    self.send_frame(4, 0x1, 0, b"")
                elif ftype == 6 and not fflags & 0x1:  # PING -> ACK
                    self.send_frame(6, 0x1, 0, payload)
                elif ftype == 1:  # HEADERS
                    if not fflags & 0x4:
                        return  # CONTINUATION unsupported: drop conn
                    block = payload
                    if fflags & 0x8:  # PADDED
                        pad = block[0]
                        block = block[1 : len(block) - pad]
                    if fflags & 0x20:  # PRIORITY
                        block = block[5:]
                    try:
                        headers_by_stream[stream] = decode_request_headers(block)
                    except _HpackError as e:
                        self._respond_error(stream, 400, str(e))
                        continue
                    if fflags & 0x1:  # END_STREAM: GET, serve now
                        t = threading.Thread(
                            target=self._handle,
                            args=(stream, headers_by_stream.pop(stream)),
                            name=f"h2-stream-{stream}", daemon=True,
                        )
                        t.start()
                elif ftype == 0:  # DATA (request bodies: ignored)
                    if fflags & 0x1 and stream in headers_by_stream:
                        h = headers_by_stream.pop(stream)
                        threading.Thread(
                            target=self._handle, args=(stream, h),
                            name=f"h2-stream-{stream}", daemon=True,
                        ).start()
                elif ftype == 7:  # GOAWAY
                    return
        except OSError:
            return
        finally:
            try:
                self.sock.close()
            except OSError:
                pass

    def _serve_h11(self, initial: bytes) -> None:
        """Keep-alive HTTP/1.1 side: object metadata, media (with Range),
        list — and the UPLOAD surface (media + resumable sessions), since
        an ``http2=True`` client's writes ride the HTTP/1.1 pool (the
        native h2 client is GET-only). Upload semantics shared with the
        h1.1 fake via handle_upload_request — one definition, two
        framings."""
        import json

        buf = initial
        while True:
            while b"\r\n\r\n" not in buf:
                chunk = self.sock.recv(65536)
                if not chunk:
                    return
                buf += chunk
            head, _, buf = buf.partition(b"\r\n\r\n")
            lines = head.decode("latin-1").split("\r\n")
            try:
                method, path, _ = lines[0].split(" ", 2)
            except ValueError:
                return
            hdrs = {}
            for ln in lines[1:]:
                k, _, v = ln.partition(":")
                hdrs[k.strip().lower()] = v.strip()

            def send(status: int, body: bytes, ctype: str, extra: str = ""):
                self.sock.sendall(
                    (
                        f"HTTP/1.1 {status} X\r\nContent-Type: {ctype}\r\n"
                        f"Content-Length: {len(body)}\r\n{extra}\r\n"
                    ).encode()
                    + body
                )

            parsed = urllib.parse.urlsplit(path)
            query = urllib.parse.parse_qs(parsed.query)
            parts = parsed.path.split("/")
            clen = int(hdrs.get("content-length", "0") or 0)
            if clen:
                while len(buf) < clen:
                    chunk = self.sock.recv(65536)
                    if not chunk:
                        return
                    buf += chunk
            req_body, buf = buf[:clen], buf[clen:]
            if len(parts) >= 2 and parts[1] == "upload":
                from tpubench.storage.fake_server import (
                    RESET_CONNECTION,
                    handle_upload_request,
                )

                resp = handle_upload_request(
                    self.backend, method, parts, query,
                    {"Content-Range": hdrs.get("content-range", "")},
                    bytes(req_body), host=hdrs.get("host", "127.0.0.1"),
                )
                if resp == RESET_CONNECTION:
                    try:
                        self.sock.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    return
                status, extra_headers, body_doc = resp
                extra = "".join(
                    f"{k}: {v}\r\n" for k, v in extra_headers.items()
                )
                send(status, json.dumps(body_doc).encode(),
                     "application/json", extra)
                continue
            if (
                method == "GET"
                and len(parts) >= 6
                and parts[1] == "storage"
                and parts[5] == "o"
                and not "/".join(parts[6:])
            ):
                # List with maxResults/pageToken pagination (parity with
                # the h1.1 fake's page surface).
                from tpubench.storage.fake_server import paginate_listing

                prefix = query.get("prefix", [""])[0]
                send(
                    200,
                    json.dumps(
                        paginate_listing(self.backend.list(prefix), query)
                    ).encode(),
                    "application/json",
                )
                continue
            if (
                method != "GET"
                or len(parts) < 7
                or parts[1] != "storage"
                or parts[5] != "o"
            ):
                send(404, b'{"error":{"code":404}}', "application/json")
                continue
            name = urllib.parse.unquote("/".join(parts[6:]))
            try:
                meta = self.backend.stat(name)
            except StorageError as e:
                send(
                    e.code or 404,
                    json.dumps({"error": {"code": e.code or 404}}).encode(),
                    "application/json",
                )
                continue
            if query.get("alt", [""])[0] == "media":
                start, end, status = 0, meta.size - 1, 200
                rng = hdrs.get("range", "")
                if rng.startswith("bytes="):
                    a, _, b = rng[6:].partition("-")
                    start = int(a)
                    end = meta.size - 1 if not b else min(int(b), meta.size - 1)
                    status = 206
                length = max(0, end - start + 1)
                try:
                    reader = self.backend.open_read(
                        name, start=start, length=length
                    )
                except StorageError as e:
                    # Same open-time fault guard as the h2 media branch:
                    # a classified status, not a dead connection thread.
                    send(
                        e.code or 500,
                        json.dumps({"error": {"code": e.code or 500}}).encode(),
                        "application/json",
                    )
                    continue
                data = bytearray()
                mv = memoryview(bytearray(65536))
                while True:
                    n = reader.readinto(mv)
                    if n <= 0:
                        break
                    data += mv[:n]
                reader.close()
                cr = (
                    f"Content-Range: bytes {start}-{end}/{meta.size}\r\n"
                    if status == 206
                    else ""
                )
                # Generation on every download (x-goog-generation): the
                # h1.1 side mirrors the h2 media branch and fake_server.
                cr += f"x-goog-generation: {meta.generation}\r\n"
                send(status, bytes(data), "application/octet-stream", cr)
            else:
                from tpubench.storage.base import object_meta_dict

                send(200, json.dumps(object_meta_dict(meta)).encode(),
                     "application/json")

    def _respond_body(self, stream: int, status: int, body: bytes) -> None:
        """One complete response: optional interim 103 block (the
        ``send_interim_1xx`` knob precedes EVERY response), then
        :status + content-length HEADERS and the body as one DATA frame
        with END_STREAM (the client advertises a 2^24-1 max frame size,
        engine.cc)."""
        hb = _hp_literal(":status", str(status)) + _hp_literal(
            "content-length", str(len(body))
        )
        try:
            if self.interim_end_stream:
                # Malformed: informational block ends the stream.
                self.send_frame(
                    1, 0x4 | 0x1, stream, _hp_literal(":status", "103")
                )
                return
            if self.send_interim_1xx:
                self.send_frame(1, 0x4, stream, _hp_literal(":status", "103"))
            self.send_frame(1, 0x4, stream, hb)
            self.send_frame(0, 0x1, stream, body)
        except OSError:
            pass

    def _respond_error(self, stream: int, status: int, msg: str) -> None:
        self._respond_body(stream, status, msg.encode())

    def _handle(self, stream: int, h: dict) -> None:
        # Effective plan for this moment (time-phased schedules switch
        # the open-time faults on/off mid-run; the shaped mid-stream
        # faults ride the backend reader below).
        fault = self.backend.fault.at()
        if fault.latency_s:
            import time

            time.sleep(fault.latency_s)
        if fault.error_rate:
            with self.backend._rng_lock:
                r = self.backend._rng.random()
            if r < fault.error_rate:
                self.backend.injected_errors += 1
                return self._respond_error(stream, 503, "injected unavailability")
        path = h.get(":path", "/")
        parsed = urllib.parse.urlsplit(path)
        query = urllib.parse.parse_qs(parsed.query)
        parts = parsed.path.split("/")
        if (
            len(parts) < 6
            or parts[1] != "storage"
            or parts[3] != "b"
            or parts[5] != "o"
        ):
            return self._respond_error(stream, 404, f"no route: {path}")
        if len(parts) == 6 or not "/".join(parts[6:]):
            # List route over h2 (`.../o?prefix=`): the whole-client
            # http2 mode sends list requests here too — same
            # maxResults/pageToken page surface as the h1.1 fake.
            import json

            from tpubench.storage.fake_server import paginate_listing

            prefix = query.get("prefix", [""])[0]
            body = json.dumps(
                paginate_listing(self.backend.list(prefix), query)
            ).encode()
            return self._respond_body(stream, 200, body)
        name = urllib.parse.unquote("/".join(parts[6:]))
        try:
            meta = self.backend.stat(name)
        except StorageError as e:
            return self._respond_error(stream, e.code or 404, str(e))
        if query.get("alt", [""])[0] != "media":
            # Object metadata over h2: the whole-client http2 mode
            # (reference ForceAttemptHTTP2, main.go:76-80) sends stat
            # requests on this connection too.
            import json

            from tpubench.storage.base import object_meta_dict

            body = json.dumps(object_meta_dict(meta)).encode()
            return self._respond_body(stream, 200, body)
        start, end, status = 0, meta.size - 1, 200
        rng = h.get("range", "")
        if rng.startswith("bytes="):
            spec = rng[len("bytes=") :]
            a, _, b = spec.partition("-")
            start = int(a)
            end = meta.size - 1 if not b else min(int(b), meta.size - 1)
            status = 206
        length = max(0, end - start + 1)
        try:
            reader = self.backend.open_read(name, start=start, length=length)
        except StorageError as e:
            # The backend's open-time fault point (distinct from the
            # error_rate gate above): a dead handler thread here would
            # leave the stream unanswered and the client waiting out a
            # socket timeout instead of seeing the classified status.
            return self._respond_error(stream, e.code or 500, str(e))
        hb = (
            _hp_literal(":status", str(status))
            + _hp_literal("content-length", str(length))
            # Generation on every media response (x-goog-generation),
            # matching the h1.1 fake server's download surface.
            + _hp_literal("x-goog-generation", str(meta.generation))
        )
        try:
            if self.interim_end_stream:
                # Malformed interim (see __init__): END_STREAM on the 103.
                self.send_frame(
                    1, 0x4 | 0x1, stream, _hp_literal(":status", "103")
                )
                return
            if self.send_interim_1xx:
                # Informational block first: END_HEADERS, no END_STREAM,
                # no content-length — the response block follows.
                self.send_frame(1, 0x4, stream, _hp_literal(":status", "103"))
            # Zero-length bodies (empty object, clamped-empty range) end
            # the stream on the HEADERS frame — there is no DATA frame to
            # carry END_STREAM and the client would otherwise wait forever.
            self.send_frame(1, 0x4 | (0x1 if length == 0 else 0), stream, hb)
            buf = bytearray(16384)
            mv = memoryview(buf)
            sent = 0
            cap = self.truncate_body_bytes
            while sent < length:
                if cap is not None and sent >= cap:
                    # Truncation fault: clean END_STREAM short of the
                    # announced content-length.
                    self.send_frame(0, 0x1, stream, b"")
                    break
                try:
                    n = reader.readinto(mv)
                except StorageError:
                    # Mid-stream fault injection: RST the stream, exactly
                    # the mid-body cut the h1.1 fake produces by closing.
                    self.send_frame(3, 0, stream, struct.pack("!I", 2))
                    return
                if n <= 0:
                    # Backend exhausted early: close the stream rather
                    # than leaving it dangling short of content-length.
                    self.send_frame(0, 0x1, stream, b"")
                    break
                sent += n
                last = sent >= length
                self.send_frame(0, 0x1 if last else 0, stream, bytes(mv[:n]))
        except OSError:
            pass
        finally:
            reader.close()


class FakeH2Server:
    """Threaded fake h2 media server; context-manager like the others.

    Plain mode speaks h2c with prior knowledge (what an ``http://``
    endpoint with ``http2=True`` means); ``tls=True`` wraps the listener
    in TLS with ALPN ``h2`` and an ephemeral self-signed cert.
    """

    def __init__(
        self,
        backend: Optional[FakeBackend] = None,
        port: int = 0,
        tls: bool = False,
        truncate_body_bytes: Optional[int] = None,
        send_interim_1xx: bool = False,
        interim_end_stream: bool = False,
    ):
        self.backend = backend or FakeBackend()
        self.truncate_body_bytes = truncate_body_bytes
        self.send_interim_1xx = send_interim_1xx
        self.interim_end_stream = interim_end_stream
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("127.0.0.1", port))
        self._sock.listen(16)
        self._tls = tls
        self.cafile = ""
        self._ctx = None
        if tls:
            from tpubench.storage.fake_server import make_self_signed_cert

            self.cafile, keyfile = make_self_signed_cert()
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(self.cafile, keyfile)
            ctx.set_alpn_protocols(["h2"])
            self._ctx = ctx
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def endpoint(self) -> str:
        host, port = self._sock.getsockname()[:2]
        scheme = "https" if self._tls else "http"
        return f"{scheme}://{host}:{port}"

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            if self._ctx is not None:
                try:
                    conn = self._ctx.wrap_socket(conn, server_side=True)
                except ssl.SSLError:
                    continue
            threading.Thread(
                target=_Conn(
                    conn, self.backend,
                    truncate_body_bytes=self.truncate_body_bytes,
                    send_interim_1xx=self.send_interim_1xx,
                    interim_end_stream=self.interim_end_stream,
                ).serve,
                name="h2-conn", daemon=True,
            ).start()

    def start(self) -> "FakeH2Server":
        self._thread = threading.Thread(
            target=self._accept_loop, name="h2-accept", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        if self._thread:
            self._thread.join(timeout=5)

    def __enter__(self) -> "FakeH2Server":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
