"""A real HTTP server speaking the GCS JSON-API object surface.

Backs the hermetic integration tests for the http client path
(SURVEY §4: "in-process HTTP server implementing the JSON object-get
surface"). Endpoints mirror what ``cloud.google.com/go/storage``'s HTTP
transport uses under the reference's read loop:

* ``GET /storage/v1/b/<bucket>/o/<object>?alt=media`` — media download,
  honoring ``Range: bytes=a-b`` (the ranged-read path our shard fetches use);
* ``GET /storage/v1/b/<bucket>/o/<object>`` — metadata;
* ``GET /storage/v1/b/<bucket>/o?prefix=`` — list;
* ``POST /upload/storage/v1/b/<bucket>/o?uploadType=media&name=`` — upload;
* ``DELETE /storage/v1/b/<bucket>/o/<object>``.

Fault injection (503s, latency) comes from the backing
:class:`~tpubench.storage.fake.FakeBackend`'s :class:`FaultPlan`, giving the
client-side retry policy something real to chew on.
"""

from __future__ import annotations

import json
import socket
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from tpubench.storage.base import StorageError
from tpubench.storage.fake import FakeBackend

# Sentinel returned by handle_upload_request: the injected fault killed
# the connection mid-part — the server must abort the socket, not answer.
RESET_CONNECTION = ("reset",)


def parse_content_range(hdr: str):
    """``Content-Range: bytes a-b/T`` → (start, total) with ``None`` for
    ``*`` on either side (``bytes */T`` is the resume probe / finalize
    form; ``bytes */*`` the pure probe). Malformed → ValueError."""
    spec = hdr.strip()
    if not spec.startswith("bytes "):
        raise ValueError(f"bad Content-Range: {hdr!r}")
    rng, _, total_s = spec[len("bytes "):].partition("/")
    total = None if total_s.strip() in ("", "*") else int(total_s)
    if rng.strip() == "*":
        return None, total
    start_s, _, _end_s = rng.partition("-")
    return int(start_s), total


def paginate_listing(items, query: dict) -> dict:
    """The GCS list page surface (``maxResults``/``pageToken``): slice the
    sorted listing into one page and stamp ``nextPageToken`` (a name
    cursor — the page starts strictly after it) when more remain. One
    definition shared by both fake servers."""
    from tpubench.storage.base import object_meta_dict

    max_results = int(query.get("maxResults", ["0"])[0] or 0)
    token = query.get("pageToken", [""])[0]
    if token:
        items = [m for m in items if m.name > token]
    page = items if max_results <= 0 else items[:max_results]
    doc = {
        "kind": "storage#objects",
        "items": [object_meta_dict(m) for m in page],
    }
    if 0 < max_results < len(items):
        doc["nextPageToken"] = page[-1].name
    return doc


def handle_upload_request(
    backend: FakeBackend, method: str, parts, query: dict,
    headers, body: bytes, host: str,
):
    """Wire-agnostic upload routing shared by BOTH fake servers (h1.1
    handler and the h2 server's HTTP/1.1 side — one resumable-upload
    semantics, two framings). Returns ``(status, extra_headers, body_dict)``
    or :data:`RESET_CONNECTION` when an injected mid-part fault must kill
    the socket.

    Routes (the GCS JSON upload surface):

    * ``POST …?uploadType=media&name=N[&ifGenerationMatch=G]`` — one-shot
      media upload, precondition honored (412 on mismatch);
    * ``POST …?uploadType=resumable&name=N[&ifGenerationMatch=G]`` —
      session open; the session URL rides the ``Location`` header;
    * ``PUT …?uploadType=resumable&upload_id=U`` + ``Content-Range`` —
      one part (``bytes a-b/*``), the finalize form (``bytes a-b/T`` /
      ``bytes */T``) or the resume probe (``bytes */*``): partial commits
      answer **308 with the committed ``Range``**, completion answers the
      object metadata, precondition mismatch 412.
    """
    from tpubench.storage.base import object_meta_dict

    if len(parts) < 6 or parts[1] != "upload":
        return 404, {}, {"error": {"code": 404, "message": "no route"}}
    bucket = parts[4]
    upload_type = query.get("uploadType", [""])[0]
    igm_raw = query.get("ifGenerationMatch", [""])[0]
    igm = int(igm_raw) if igm_raw else None

    def err(e: StorageError):
        return (e.code or 500), {}, {
            "error": {"code": e.code or 500, "message": str(e)}
        }

    if method == "POST" and upload_type == "media":
        name = query.get("name", [""])[0]
        if not name:
            return 400, {}, {"error": {"code": 400, "message": "missing name"}}
        try:
            meta = backend.write(name, body, if_generation_match=igm)
        except StorageError as e:
            return err(e)
        return 200, {}, object_meta_dict(meta)
    if method == "POST" and upload_type == "resumable":
        name = query.get("name", [""])[0]
        if not name:
            return 400, {}, {"error": {"code": 400, "message": "missing name"}}
        uid = backend.begin_upload(name, if_generation_match=igm)
        session = (
            f"http://{host}/upload/storage/v1/b/{bucket}/o"
            f"?uploadType=resumable&upload_id={uid}"
        )
        return 200, {
            "Location": session, "X-GUploader-UploadID": uid,
        }, {}
    if method == "PUT" and upload_type == "resumable":
        uid = query.get("upload_id", [""])[0]
        try:
            start, total = parse_content_range(
                headers.get("Content-Range", "") or
                headers.get("content-range", "")
            )
        except ValueError as e:
            return 400, {}, {"error": {"code": 400, "message": str(e)}}
        try:
            committed, final = backend.upload_status(uid)
            if final is not None:
                # Idempotent replay of a part/finalize whose response was
                # lost: the object is already committed — answer its meta.
                return 200, {}, object_meta_dict(final)
            if body:
                if start is None:
                    return 400, {}, {"error": {
                        "code": 400,
                        "message": "data part needs an explicit range",
                    }}
                if start > committed:
                    # Client ran ahead of the server's watermark: resync
                    # via 308 + Range, the resume contract.
                    return _resume_308(committed)
                committed = backend.upload_append(uid, start, body)
            if total is not None and committed == total:
                meta = backend.finalize_upload(uid, total=total)
                return 200, {}, object_meta_dict(meta)
        except StorageError as e:
            if e.code == 104:
                return RESET_CONNECTION
            return err(e)
        return _resume_308(committed)
    return 404, {}, {"error": {"code": 404, "message": "no upload route"}}


def _resume_308(committed: int):
    hdrs = {"Range": f"bytes=0-{committed - 1}"} if committed > 0 else {}
    return 308, hdrs, {}


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"  # keep-alive: reference tunes idle conns (main.go:31-32)
    server_version = "fake-gcs/0.1"

    # Quiet by default; tests can flip this.
    verbose = False

    def log_message(self, fmt, *args):  # noqa: D102
        if self.verbose:
            super().log_message(fmt, *args)

    # ------------------------------------------------------------ helpers --
    @property
    def backend(self) -> FakeBackend:
        return self.server.backend  # type: ignore[attr-defined]

    def _send_json(self, code: int, obj: dict,
                   extra_headers: Optional[dict] = None) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json; charset=UTF-8")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _send_error_json(self, code: int, message: str) -> None:
        self._send_json(code, {"error": {"code": code, "message": message}})

    def _maybe_inject_fault(self) -> bool:
        # The effective plan for THIS moment: time-phased schedules make
        # the open-time faults turn on and off mid-run.
        fault = self.backend.fault.at()
        if fault.latency_s:
            time.sleep(fault.latency_s)
        if fault.error_rate:
            with self.backend._rng_lock:
                r = self.backend._rng.random()
            if r < fault.error_rate:
                self.backend.injected_errors += 1
                self._send_error_json(503, "injected unavailability")
                return True
        return False

    def _parse(self):
        parsed = urllib.parse.urlsplit(self.path)
        query = urllib.parse.parse_qs(parsed.query)
        parts = parsed.path.split("/")
        return parsed.path, parts, query

    def _object_name(self, parts) -> Optional[str]:
        # /storage/v1/b/<bucket>/o/<object> — object may be %2F-encoded.
        if len(parts) >= 7 and parts[1] == "storage" and parts[3] == "b" and parts[5] == "o":
            return urllib.parse.unquote("/".join(parts[6:]))
        return None

    def _range(self) -> Optional[tuple[int, Optional[int]]]:
        hdr = self.headers.get("Range")
        if not hdr or not hdr.startswith("bytes="):
            return None
        spec = hdr[len("bytes=") :]
        start_s, _, end_s = spec.partition("-")
        start = int(start_s)
        end = int(end_s) if end_s else None
        return start, end

    # ------------------------------------------------------------- verbs --
    def do_GET(self):  # noqa: N802
        path, parts, query = self._parse()
        if self._maybe_inject_fault():
            return
        from tpubench.storage.base import object_meta_dict

        try:
            name = self._object_name(parts)
            if name:  # object media or metadata
                if query.get("alt", [""])[0] == "media":
                    return self._get_media(name)
                meta = self.backend.stat(name)
                return self._send_json(200, object_meta_dict(meta))
            if len(parts) >= 6 and parts[3] == "b" and parts[5] == "o":  # list
                prefix = query.get("prefix", [""])[0]
                # maxResults/pageToken pagination (meta-storm's multi-page
                # lists; one unbounded page when maxResults is absent).
                return self._send_json(
                    200, paginate_listing(self.backend.list(prefix), query)
                )
            self._send_error_json(404, f"no route: {path}")
        except StorageError as e:
            self._send_error_json(e.code or 500, str(e))

    def _get_media(self, name: str) -> None:
        rng = self._range()
        meta = self.backend.stat(name)
        start, end = 0, meta.size - 1
        code = 200
        if rng is not None:
            start = rng[0]
            end = meta.size - 1 if rng[1] is None else min(rng[1], meta.size - 1)
            code = 206
        length = max(0, end - start + 1)
        reader = self.backend.open_read(name, start=start, length=length)
        self.send_response(code)
        self.send_header("Content-Type", "application/octet-stream")
        self.send_header("Content-Length", str(length))
        # The real media surface stamps the served object's generation on
        # every download — what clients (and the pipeline chunk cache)
        # use to detect an overwrite without a second stat round-trip.
        self.send_header("x-goog-generation", str(meta.generation))
        if code == 206:
            self.send_header("Content-Range", f"bytes {start}-{end}/{meta.size}")
        self.end_headers()
        # Stream in chunks — the server is not the component under test;
        # the client's granule size governs the benchmark. On single-core
        # hosts the server's Python loop competes with the client for the
        # CPU, so bench-scale runs raise chunk_bytes (fewer interpreter
        # iterations per MB; sendall of a big memoryview is one syscall
        # path either way).
        buf = bytearray(getattr(self.server, "chunk_bytes", 256 * 1024))
        mv = memoryview(buf)
        try:
            while True:
                try:
                    n = reader.readinto(mv)
                except StorageError:
                    # Mid-body fault (injected reset / read error): the
                    # headers are already on the wire, so a JSON error here
                    # would be consumed as BODY bytes (content-length
                    # framing) and silently corrupt the stream. Kill the
                    # connection abruptly instead — the reset shape the
                    # chaos plane wants, and what a dying proxy produces.
                    self.close_connection = True
                    try:
                        self.connection.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    return
                if n <= 0:
                    break
                self.wfile.write(mv[:n])
        finally:
            reader.close()

    def _upload(self, method: str) -> None:
        """POST/PUT upload surface: media + resumable sessions, shared
        with the h2 server's HTTP/1.1 side via handle_upload_request."""
        path, parts, query = self._parse()
        if self._maybe_inject_fault():
            return
        n = int(self.headers.get("Content-Length", "0"))
        data = self.rfile.read(n) if n else b""
        resp = handle_upload_request(
            self.backend, method, parts, query, self.headers, data,
            host=self.headers.get("Host", "127.0.0.1"),
        )
        if resp == RESET_CONNECTION:
            # Injected mid-part fault: the reset shape — kill the socket
            # abruptly, exactly what the media path does mid-body.
            self.close_connection = True
            try:
                self.connection.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            return
        status, extra_headers, body = resp
        self._send_json(status, body, extra_headers)

    def do_POST(self):  # noqa: N802
        self._upload("POST")

    def do_PUT(self):  # noqa: N802
        self._upload("PUT")

    def do_DELETE(self):  # noqa: N802
        _, parts, _ = self._parse()
        name = self._object_name(parts)
        if not name:
            return self._send_error_json(404, "no route")
        try:
            self.backend.delete(name)
        except StorageError as e:
            return self._send_error_json(e.code or 500, str(e))
        self.send_response(204)
        self.send_header("Content-Length", "0")
        self.end_headers()


class FakeGcsServer:
    """Threaded fake-GCS server; use as a context manager in tests.

    ``tls=True`` wraps the listener in TLS with an ephemeral self-signed
    certificate (SAN: localhost + 127.0.0.1) so client TLS paths — the
    Python pool's ssl context and the native engine's OpenSSL layer — can
    be exercised hermetically; ``cafile`` then points at the PEM to trust.
    """

    def __init__(
        self,
        backend: Optional[FakeBackend] = None,
        port: int = 0,
        tls: bool = False,
        chunk_bytes: int = 256 * 1024,
    ):
        self.backend = backend or FakeBackend()
        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), _Handler)
        self._httpd.backend = self.backend  # type: ignore[attr-defined]
        self._httpd.chunk_bytes = chunk_bytes  # type: ignore[attr-defined]
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None
        self._tls = tls
        self.cafile = ""
        if tls:
            import ssl

            self.cafile, keyfile = make_self_signed_cert()
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(self.cafile, keyfile)
            self._httpd.socket = ctx.wrap_socket(
                self._httpd.socket, server_side=True
            )

    @property
    def endpoint(self) -> str:
        host, port = self._httpd.server_address[:2]
        scheme = "https" if self._tls else "http"
        return f"{scheme}://{host}:{port}"

    def start(self) -> "FakeGcsServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="fake-gcs-http",
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)

    def __enter__(self) -> "FakeGcsServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def make_self_signed_cert(hostname: str = "localhost") -> tuple[str, str]:
    """Ephemeral self-signed server certificate (SAN: ``hostname`` +
    127.0.0.1), written to a temp dir. Returns ``(certfile, keyfile)`` —
    the cert PEM doubles as the CA bundle clients should trust.

    Generated with ``cryptography`` when importable, else the
    ``openssl`` CLI (hermetic CI images often ship the binary but not
    the Python package); raises StorageError when neither exists."""
    import datetime
    import ipaddress
    import tempfile

    try:
        from cryptography import x509
        from cryptography.hazmat.primitives import hashes, serialization
        from cryptography.hazmat.primitives.asymmetric import rsa
        from cryptography.x509.oid import NameOID
    except ImportError:
        return _make_self_signed_cert_cli(hostname)

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, hostname)])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=1))
        .add_extension(
            x509.SubjectAlternativeName(
                [
                    x509.DNSName(hostname),
                    x509.IPAddress(ipaddress.IPv4Address("127.0.0.1")),
                ]
            ),
            critical=False,
        )
        .sign(key, hashes.SHA256())
    )
    d = tempfile.mkdtemp(prefix="tpubench-tls-")
    certfile = f"{d}/cert.pem"
    keyfile = f"{d}/key.pem"
    with open(certfile, "wb") as f:
        f.write(cert.public_bytes(serialization.Encoding.PEM))
    with open(keyfile, "wb") as f:
        f.write(
            key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.TraditionalOpenSSL,
                serialization.NoEncryption(),
            )
        )
    return certfile, keyfile


def _make_self_signed_cert_cli(hostname: str) -> tuple[str, str]:
    import shutil
    import subprocess
    import tempfile

    exe = shutil.which("openssl")
    if exe is None:
        raise StorageError(
            "self-signed TLS cert needs the `cryptography` package or "
            "an `openssl` binary — neither found",
            transient=False,
        )
    d = tempfile.mkdtemp(prefix="tpubench-tls-")
    certfile = f"{d}/cert.pem"
    keyfile = f"{d}/key.pem"
    proc = subprocess.run(
        [
            exe, "req", "-x509", "-newkey", "rsa:2048", "-nodes",
            "-keyout", keyfile, "-out", certfile, "-days", "1",
            "-subj", f"/CN={hostname}",
            "-addext", f"subjectAltName=DNS:{hostname},IP:127.0.0.1",
        ],
        capture_output=True, text=True, timeout=30,
    )
    if proc.returncode != 0:
        raise StorageError(
            f"openssl cert generation failed: {proc.stderr.strip()}",
            transient=False,
        )
    return certfile, keyfile
