"""GCS gRPC (storage v2) backend.

Reference parity (``CreateGrpcClient``, main.go:106-117):

* **DirectPath**: via the ``google-c2p`` resolver + compute-engine channel
  credentials — the grpcio mechanism equivalent to the Go client's rls/xds
  blank imports (main.go:24-26). The env-var gate is set only around
  channel creation, like main.go:107-113. Preconditions are validated
  loudly (default endpoint only; needs a DirectPath-eligible GCP VM at
  runtime); it is never a silent no-op knob.
* **Single-connection pool**: ``GrpcConnPoolSize = 1`` (main.go:30,111) —
  one shared channel by default; >1 round-robins.
* **2 MB chunking**: the gRPC server streams ``ReadObjectResponse`` messages
  of ≤2 MiB — the documented reason the reference sized its copy buffer at
  2 MB (comment main.go:123-125). The reader hands each message's bytes out
  through ``readinto`` without re-buffering whole objects.

Built on the raw generated stubs (``google.cloud._storage_v2.types``) over a
bare channel rather than the GAPIC client, so the hermetic fake server
(:mod:`fake_grpc_server`) and the benchmark share one code path and the
hot loop has no client-library overhead in it.

Two modes, one surface:

* **library mode** — ``grpcio`` + the generated storage-v2 types, when
  both import (and always when an explicit ``channel`` is injected);
* **wire mode** — the dependency-free :mod:`tpubench.storage.grpc_wire`
  stack (hand-rolled protobuf + gRPC framing over raw h2 frames) when
  they don't. Hermetic endpoints only: it carries no auth stack, so it
  refuses ``googleapis.com`` loudly instead of failing UNAUTHENTICATED.

Resumable writes (``open_write``) speak StartResumableWrite →
BidiWriteObject with lockstep persisted-size acks → QueryWriteStatus
re-probe on break → idempotent finalize, in both modes — composed under
``_ResumingWriter`` so ckpt-save rides gRPC through upload-side chaos.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from typing import Optional

try:  # Library mode needs BOTH grpcio and the generated storage-v2 types.
    import grpc
    from google.cloud._storage_v2 import types as s2

    _HAVE_LIB = True
except ImportError:  # Wire mode: tpubench.storage.grpc_wire, no deps.
    grpc = None  # type: ignore[assignment]
    s2 = None  # type: ignore[assignment]
    _HAVE_LIB = False

from tpubench.config import TransportConfig
from tpubench.obs.flight import annotate
from tpubench.obs.flight import note_phase as flight_note
from tpubench.obs.tracing import NoopTracer, SpanCarrier
from tpubench.storage.base import ObjectMeta, StorageError
from tpubench.storage.grpc_wire import proto as wp
from tpubench.storage.grpc_wire.client import GrpcWireChannel

_SVC = "/google.storage.v2.Storage"

_TRANSIENT_CODES = (
    {
        grpc.StatusCode.UNAVAILABLE,
        grpc.StatusCode.DEADLINE_EXCEEDED,
        grpc.StatusCode.RESOURCE_EXHAUSTED,
        grpc.StatusCode.ABORTED,
        grpc.StatusCode.INTERNAL,
    }
    if _HAVE_LIB
    else frozenset()
)

# gRPC server chunk ceiling (storage v2 ServiceConstants.MAX_READ_CHUNK_BYTES
# is 2 MiB) — mirrored by the fake server.
MAX_READ_CHUNK = 2 * 1024 * 1024

# grpc-status values (numeric: the native h2 path reports raw ints) whose
# retry classification mirrors _TRANSIENT_CODES above.
_TRANSIENT_STATUS_INTS = {4, 8, 10, 13, 14}  # DEADLINE_EXCEEDED,
# RESOURCE_EXHAUSTED, ABORTED, INTERNAL, UNAVAILABLE (mirrors
# _TRANSIENT_CODES above: UNKNOWN is NOT transient there either)
_STATUS_HTTPISH = {5: 404, 11: 416, 14: 503}


def _wrap_rpc_error(e: grpc.RpcError, what: str) -> StorageError:
    code = e.code() if hasattr(e, "code") else None
    transient = code in _TRANSIENT_CODES
    http_ish = {
        grpc.StatusCode.NOT_FOUND: 404,
        grpc.StatusCode.UNAVAILABLE: 503,
        grpc.StatusCode.OUT_OF_RANGE: 416,
    }.get(code, 0)
    return StorageError(
        f"{what}: {code} {e.details() if hasattr(e, 'details') else e}",
        transient=transient,
        code=http_ish,
    )


class _GrpcReader:
    """Streams ReadObjectResponse messages; leftover message bytes are
    carried between ``readinto`` calls (no whole-object buffering, no
    per-chunk copies — ``readinto`` slices a memoryview straight over the
    message's content bytes).

    First-byte stamping: the read stub's deserializer is wrapped to stamp
    arrival BEFORE protobuf parsing (``_stamped_read_deserializer``), so
    ``first_byte_ns`` measures network arrival of the first response
    message, not arrival + 2 MiB of proto decode.

    ``carrier`` (optional) is the client-internal request span (OC-bridge
    analog): ``first_byte`` event on the first message; the span ends at
    close — with the error attached when the stream failed, so failed
    reads export as failed spans.
    """

    def __init__(self, stream, carrier=None):
        self._stream = stream
        self._pending = memoryview(b"")
        self.first_byte_ns: Optional[int] = None
        self._done = False
        self._carrier = carrier

    def readinto(self, buf: memoryview) -> int:
        if self._done and not self._pending:
            return 0
        while not self._pending:
            try:
                item = next(self._stream, None)
            except grpc.RpcError as e:
                self._done = True
                err = _wrap_rpc_error(e, "ReadObject stream")
                if self._carrier is not None:
                    self._carrier.close(err)
                raise err from e
            if item is None:
                self._done = True
                return 0
            arrival_ns, msg = item
            if self.first_byte_ns is None:
                self.first_byte_ns = arrival_ns
                if self._carrier is not None:
                    self._carrier.event("first_byte")
            content = msg.checksummed_data.content
            if content:
                self._pending = memoryview(content)
        n = min(len(buf), len(self._pending))
        buf[:n] = self._pending[:n]
        self._pending = self._pending[n:]
        return n

    def close(self) -> None:
        try:
            self._stream.cancel()
        except Exception:
            pass
        self._done = True
        if self._carrier is not None:
            self._carrier.close()  # idempotent; failure paths closed it already


def _stamped_read_deserializer(b: bytes):
    """Arrival stamp taken on the raw wire bytes BEFORE proto decode: the
    first-byte latency must not include deserializing a 2 MiB message."""
    return time.perf_counter_ns(), s2.ReadObjectResponse.deserialize(b)


class _WireGrpcReader:
    """Wire-mode twin of :class:`_GrpcReader`: streams framed
    ReadObjectResponse messages off a :class:`WireCall`, carrying
    leftover message bytes between ``readinto`` calls. The first-byte
    stamp is taken on the raw message bytes BEFORE protobuf decode,
    matching the library path's wrapped deserializer."""

    def __init__(self, call, carrier=None):
        self._call = call
        self._pending = memoryview(b"")
        self.first_byte_ns: Optional[int] = None
        self._done = False
        self._carrier = carrier

    def readinto(self, buf: memoryview) -> int:
        if self._done and not self._pending:
            return 0
        while not self._pending:
            try:
                raw = self._call.recv_message()
            except StorageError as e:
                self._done = True
                self._call.cancel()
                if self._carrier is not None:
                    self._carrier.close(e)
                raise
            if raw is None:
                self._done = True
                return 0
            arrival_ns = time.perf_counter_ns()
            msg = wp.ReadObjectResponse.decode(raw)
            if self.first_byte_ns is None:
                self.first_byte_ns = arrival_ns
                if self._carrier is not None:
                    self._carrier.event("first_byte")
            cd = msg.checksummed_data
            if cd is not None and cd.content:
                self._pending = memoryview(cd.content)
        n = min(len(buf), len(self._pending))
        buf[:n] = self._pending[:n]
        self._pending = self._pending[n:]
        return n

    def close(self) -> None:
        if self._done:
            self._call.close()  # clean end: the conn can be reused
        else:
            self._call.cancel()  # abandoned mid-stream: RST + discard
        self._done = True
        if self._carrier is not None:
            self._carrier.close()


class GcsGrpcBackend:
    def __init__(
        self,
        bucket: str,
        transport: Optional[TransportConfig] = None,
        channel=None,
        tracer=None,
    ):
        self.bucket = bucket
        self.transport = transport or TransportConfig()
        self._tracer = tracer or NoopTracer()
        n = max(1, self.transport.grpc_conn_pool_size)
        # Mode: library (grpcio + storage-v2 types) when importable or a
        # channel is injected; the dependency-free wire stack otherwise.
        self._wire = not _HAVE_LIB and channel is None
        if channel is not None:
            if not _HAVE_LIB:
                raise StorageError(
                    "explicit grpc channel needs grpcio + "
                    "google.cloud._storage_v2 installed",
                    transient=False,
                )
            self._channels = [channel]
            self._owns_channels = False
        elif self._wire:
            self._channels = [self._make_wire_channel() for _ in range(n)]
            self._owns_channels = True
        else:
            self._channels = [self._make_channel() for _ in range(n)]
            self._owns_channels = True
        self._rr = itertools.cycle(range(len(self._channels)))
        self._rr_lock = threading.Lock()
        self._stubs = (
            []
            if self._wire
            else [self._make_stubs(ch) for ch in self._channels]
        )
        # Native-receive pool (transport.native_receive): engine tb_conn
        # handles carrying h2 sessions; sequential RPCs reuse a handle.
        # Shared pool machinery (same discipline as gcs_http's native
        # path), lazily built on first use.
        self._native_pool_obj = None
        self._native_pool_lock = threading.Lock()
        self._native_bufpool = None
        self._native_tokens = None
        self._stat_cache: dict[str, int] = {}
        self._stat_cache_lock = threading.Lock()

    # ------------------------------------------------------- native pool --
    def _native_pool(self):
        with self._native_pool_lock:
            if self._native_pool_obj is None:
                from tpubench.storage.native_pool import build_native_pool

                if self.transport.directpath and not (
                    self.transport.endpoint or ""
                ).startswith("insecure://"):
                    # The native h2 client dials the endpoint directly; the
                    # google-c2p resolver never runs. Same no-silent-no-op
                    # rule as the Python channel path.
                    import warnings

                    warnings.warn(
                        "native_receive bypasses DirectPath: the native h2 "
                        "client connects straight to the endpoint (public "
                        "path); transport.directpath does not apply",
                        stacklevel=3,
                    )
                host, port, tls = self._native_endpoint()
                self._native_pool_obj = build_native_pool(
                    self.transport, host, port, tls=tls, alpn_h2=tls
                )
                self._native_bufpool = self._native_pool_obj.buffers
        return self._native_pool_obj

    def _native_auth_headers(self) -> str:
        """Authorization metadata for the native h2 client — same token
        sources as the HTTP path (ADC / key file; anonymous for non-Google
        endpoints, so hermetic runs send no header)."""
        from tpubench.storage.auth import make_token_source

        if self._native_tokens is None:
            self._native_tokens = make_token_source(
                self.transport.key_file, self.transport.endpoint
            )
        tok = self._native_tokens.token()
        return f"authorization: Bearer {tok}\r\n" if tok else ""

    @property
    def _native_idle(self) -> list[int]:
        return self._native_pool().idle

    @property
    def native_conn_stats(self) -> dict:
        return self._native_pool().stats

    # ------------------------------------------------------ wire channel --
    def _make_wire_channel(self) -> GrpcWireChannel:
        endpoint = self.transport.endpoint or "storage.googleapis.com:443"
        if self.transport.directpath and not (
            endpoint in ("storage.googleapis.com:443", "storage.googleapis.com")
        ):
            # Same no-silent-no-op rule (and the same message) as the
            # library-mode channel factory below.
            import warnings

            warnings.warn(
                f"transport.directpath=True ignored for custom endpoint "
                f"{endpoint!r}: DirectPath serves storage.googleapis.com only",
                stacklevel=3,
            )
        if "googleapis.com" in endpoint:
            # The wire stack carries no auth/resolver machinery: real GCS
            # (and DirectPath, which only serves it) needs library mode.
            raise StorageError(
                "grpc wire mode is hermetic-only: point transport.endpoint "
                "at a test server (e.g. FakeGrpcWireServer), or install "
                "grpcio + google.cloud._storage_v2 for real GCS",
                transient=False,
            )
        host, port, tls = self._native_endpoint()
        return GrpcWireChannel(
            host,
            port,
            tls=tls,
            cafile=self.transport.tls_ca_file or None,
            insecure_skip_verify=self.transport.tls_insecure_skip_verify,
        )

    def _wire_chan(self) -> GrpcWireChannel:
        with self._rr_lock:
            return self._channels[next(self._rr)]

    def _wire_unary(self, method: str, req: "wp.Msg") -> bytes:
        """One wire-mode unary RPC; errors arrive pre-classified from
        the frame layer (grpc-status → StorageError mapping)."""
        return self._wire_chan().unary(method, req.encode())

    # ----------------------------------------------------------- channel --
    def _make_channel(self) -> "grpc.Channel":
        endpoint = self.transport.endpoint or "storage.googleapis.com:443"
        opts = [
            ("grpc.max_receive_message_length", 16 * 1024 * 1024),
            ("grpc.keepalive_time_ms", 30000),
        ]
        if self.transport.directpath:
            if endpoint in ("storage.googleapis.com:443", "storage.googleapis.com"):
                return self._make_directpath_channel(opts)
            # DirectPath serves real GCS only; with a custom/fake endpoint
            # the knob cannot apply — say so visibly (never a silent no-op)
            # and use the plain channel.
            import warnings

            warnings.warn(
                f"transport.directpath=True ignored for custom endpoint "
                f"{endpoint!r}: DirectPath serves storage.googleapis.com only",
                stacklevel=3,
            )
        if endpoint.startswith("insecure://"):
            return grpc.insecure_channel(endpoint[len("insecure://"):], opts)
        root = None
        if self.transport.tls_ca_file:
            # Private CA (hermetic TLS test servers) — same knob as the
            # HTTP pool and the native conn layer.
            with open(self.transport.tls_ca_file, "rb") as f:
                root = f.read()
        creds = grpc.ssl_channel_credentials(root_certificates=root)
        if "googleapis.com" in endpoint:
            creds = grpc.composite_channel_credentials(
                creds, self._call_credentials()
            )
        return grpc.secure_channel(endpoint, creds, opts)

    @staticmethod
    def _call_credentials() -> grpc.CallCredentials:
        import google.auth
        import google.auth.transport.grpc
        import google.auth.transport.requests

        from tpubench.storage.auth import GCS_SCOPE

        gcreds, _ = google.auth.default(scopes=[GCS_SCOPE])
        return grpc.metadata_call_credentials(
            google.auth.transport.grpc.AuthMetadataPlugin(
                gcreds, google.auth.transport.requests.Request()
            )
        )

    def _make_directpath_channel(self, opts: list) -> grpc.Channel:
        """Real DirectPath from grpcio: the ``google-c2p`` resolver picks
        DirectPath backends over the VPC fabric when the VM is eligible,
        falling back to the public path otherwise — the grpcio equivalent of
        the Go client's rls/xds blank imports + env var
        (``main.go:24-26,107-113``; a plain ``grpc.secure_channel`` with the
        env var set does NOTHING in Python, so the previous env-var-only
        arrangement was a no-op and is gone). Needs grpc-core built with xds
        (standard wheels are) and google-auth for the compute-engine
        credentials DirectPath requires — import failures surface loudly.
        """
        # GOOGLE_CLOUD_ENABLE_DIRECT_PATH_XDS gates the c2p resolver's xds
        # path inside grpc-core — set only around channel creation, exactly
        # like the reference (main.go:107-113).
        saved = os.environ.get("GOOGLE_CLOUD_ENABLE_DIRECT_PATH_XDS")
        os.environ["GOOGLE_CLOUD_ENABLE_DIRECT_PATH_XDS"] = "true"
        try:
            creds = grpc.compute_engine_channel_credentials(
                self._call_credentials()
            )
            return grpc.secure_channel("google-c2p:///storage.googleapis.com",
                                       creds, opts)
        finally:
            if saved is None:
                os.environ.pop("GOOGLE_CLOUD_ENABLE_DIRECT_PATH_XDS", None)
            else:
                os.environ["GOOGLE_CLOUD_ENABLE_DIRECT_PATH_XDS"] = saved

    def _make_stubs(self, ch: grpc.Channel) -> dict:
        return {
            "read": ch.unary_stream(
                f"{_SVC}/ReadObject",
                request_serializer=s2.ReadObjectRequest.serialize,
                response_deserializer=_stamped_read_deserializer,
            ),
            "get": ch.unary_unary(
                f"{_SVC}/GetObject",
                request_serializer=s2.GetObjectRequest.serialize,
                response_deserializer=s2.Object.deserialize,
            ),
            "list": ch.unary_unary(
                f"{_SVC}/ListObjects",
                request_serializer=s2.ListObjectsRequest.serialize,
                response_deserializer=s2.ListObjectsResponse.deserialize,
            ),
            "delete": ch.unary_unary(
                f"{_SVC}/DeleteObject",
                request_serializer=s2.DeleteObjectRequest.serialize,
                response_deserializer=_empty_deserializer,
            ),
            "write": ch.stream_unary(
                f"{_SVC}/WriteObject",
                request_serializer=s2.WriteObjectRequest.serialize,
                response_deserializer=s2.WriteObjectResponse.deserialize,
            ),
            "start_resumable": ch.unary_unary(
                f"{_SVC}/StartResumableWrite",
                request_serializer=s2.StartResumableWriteRequest.serialize,
                response_deserializer=(
                    s2.StartResumableWriteResponse.deserialize
                ),
            ),
            "query_write": ch.unary_unary(
                f"{_SVC}/QueryWriteStatus",
                request_serializer=s2.QueryWriteStatusRequest.serialize,
                response_deserializer=s2.QueryWriteStatusResponse.deserialize,
            ),
            "bidi_write": ch.stream_stream(
                f"{_SVC}/BidiWriteObject",
                request_serializer=s2.BidiWriteObjectRequest.serialize,
                response_deserializer=s2.BidiWriteObjectResponse.deserialize,
            ),
        }

    def _stub(self) -> dict:
        with self._rr_lock:
            return self._stubs[next(self._rr)]

    @property
    def _bucket_path(self) -> str:
        return f"projects/_/buckets/{self.bucket}"

    # ------------------------------------------------------ native path --
    def _native_endpoint(self) -> tuple[str, int, bool]:
        """(host, port, tls) for the native h2 client. ``insecure://`` =
        plaintext h2c prior knowledge (what an insecure gRPC port speaks);
        anything else handshakes TLS through the engine's conn layer."""
        ep = self.transport.endpoint or "storage.googleapis.com:443"
        tls = True
        if ep.startswith("insecure://"):
            ep = ep[len("insecure://"):]
            tls = False
        host, _, port = ep.partition(":")
        return host, int(port or 443), tls

    def _open_read_native(self, name: str, start: int, length: Optional[int]):
        """Native gRPC receive (SURVEY §2.5.1's gRPC half): the engine's
        hand-rolled h2 client runs the ReadObject RPC and lands content
        bytes in a pre-registered aligned buffer with a native first-byte
        stamp. Connection handles pool with the shared
        :class:`~tpubench.storage.native_pool.NativeConnPool` discipline
        (h2 streams 1, 3, 5, … per connection; one stale-use retry)."""
        from tpubench.native.engine import (
            PERMANENT_CODES,
            TB_EGRPC,
            TB_ETOOBIG,
            NativeError,
        )
        from tpubench.storage.gcs_http import _NativeBufReader

        pool = self._native_pool()  # raises when the engine is unavailable
        engine = pool.engine
        host, port, _ = self._native_endpoint()
        if length is None:
            with self._stat_cache_lock:
                size = self._stat_cache.get(name)
            if size is None:
                size = self.stat(name).size
                with self._stat_cache_lock:
                    self._stat_cache[name] = size
            want = size - start
        else:
            want = length
        buf = self._native_bufpool.acquire(max(4096, want))
        metadata = self._native_auth_headers()

        def do_request(conn: int) -> dict:
            with self._tracer.span(
                "gcs_grpc.read_native", object=name, bucket=self.bucket
            ) as sp:
                flight_note("stream_open")
                r = engine.grpc_read(
                    conn, f"{host}:{port}", self._bucket_path, name, buf,
                    read_offset=start, read_limit=length or 0,
                    headers=metadata,
                )
                sp.event("first_byte", native_ns=r["first_byte_ns"])
            return r

        try:
            # An explicit grpc-status is a server ANSWER, not pool
            # staleness — never burn a stale retry on it; neither on
            # permanent protocol-shape codes (they reproduce identically
            # on a fresh socket — the pool default's invariant, composed
            # here with the grpc-status rule).
            r = pool.run(
                do_request,
                retry_stale=lambda e: (
                    e.code not in PERMANENT_CODES
                    and getattr(e, "grpc_status", -1) < 0
                ),
            )
        except StorageError:
            self._native_bufpool.release(buf)  # connect failure, classified
            raise
        except NativeError as e:
            self._native_bufpool.release(buf)
            with self._stat_cache_lock:
                self._stat_cache.pop(name, None)
            st = getattr(e, "grpc_status", -1)
            if e.code == TB_EGRPC and st >= 0:
                raise StorageError(
                    f"native ReadObject {name}: grpc-status {st}",
                    transient=st in _TRANSIENT_STATUS_INTS,
                    code=_STATUS_HTTPISH.get(st, 0),
                ) from e
            transient = e.code not in PERMANENT_CODES
            if e.code == TB_ETOOBIG and length is None:
                # Buffer was sized from the (just-invalidated) stat cache;
                # the object may have grown — one retry re-stats and
                # re-sizes, like the HTTP native path.
                transient = True
            raise StorageError(
                f"native ReadObject {name}: {e}", transient=transient
            ) from e
        except BaseException:
            # Includes KeyboardInterrupt: an interrupted in-flight GET must
            # not strand a multi-MB receive buffer.
            self._native_bufpool.release(buf)
            raise
        # A short stream with no contradicting grpc-status (trailers may be
        # huffman-coded, which the structural HPACK parse skips) must never
        # pass as a short success. Full reads compare against object
        # metadata; ranged reads can only be checked when a cached stat
        # bounds the range (a range past EOF legitimately returns less).
        expected = want
        if length is not None:
            with self._stat_cache_lock:
                size = self._stat_cache.get(name)
            expected = min(want, max(0, size - start)) if size is not None else 0
        if r["grpc_status"] != 0 and r["length"] < expected:
            self._native_bufpool.release(buf)
            with self._stat_cache_lock:
                self._stat_cache.pop(name, None)
            raise StorageError(
                f"native ReadObject {name}: short stream "
                f"({r['length']} of {expected} bytes)", transient=True
            )
        return _NativeBufReader(
            buf, r["length"], r["first_byte_ns"],
            release=self._native_bufpool.release,
        )

    def read_ranges(self, name: str, ranges, buffers) -> list:
        """Concurrent ReadObject streams on ONE native connection —
        grpc-go's default multiplexing shape (go.mod:20), exposed at the
        backend level for shard-fan workloads: range *i* (``(start,
        length)``) lands in ``buffers[i]`` (any writable contiguous byte
        buffer, e.g. a numpy shard buffer). Returns a per-range list of
        ``None`` (success: exactly ``length`` bytes landed) or a
        classified :class:`StorageError` — per-stream failures (NOT_FOUND,
        short stream) touch only their range; connection-fatal failures
        classify onto every unfinished range. One whole-batch retransmit
        when the first use of a pooled connection fails before any
        completion (standard stale-pool discipline). Requires
        ``transport.native_receive``.
        """
        import numpy as np

        from tpubench.native.engine import PERMANENT_CODES

        n = len(ranges)
        done: list[bool] = [False] * n
        errs: list = [None] * n
        addrs: list[int] = []
        for i, ((start, length), b) in enumerate(zip(ranges, buffers)):
            arr = b if isinstance(b, np.ndarray) else np.frombuffer(b, np.uint8)
            # The engine writes `length` contiguous bytes through the raw
            # pointer: a read-only view (bytes) or a strided slice would
            # be silent memory corruption, not an error.
            if not (arr.flags.writeable and arr.flags.c_contiguous):
                raise ValueError(
                    f"range {i}: buffer must be writable and C-contiguous"
                )
            if arr.nbytes < length:
                raise ValueError(
                    f"range {i}: buffer {arr.nbytes} < length {length}"
                )
            addrs.append(arr.ctypes.data)
            if length == 0:
                done[i] = True
        if all(done):
            return errs

        def classify(i: int, c: dict):
            length = ranges[i][1]
            if c["result"] < 0:
                st = c["grpc_status"]
                if st > 0:
                    return StorageError(
                        f"ReadObject {name} range {i}: grpc-status {st}",
                        transient=st in _TRANSIENT_STATUS_INTS,
                        code=_STATUS_HTTPISH.get(st, 0),
                    )
                return StorageError(
                    f"ReadObject {name} range {i}: stream error {c['result']}",
                    transient=c["result"] not in PERMANENT_CODES,
                )
            if c["result"] != length:
                # The server must deliver the bounded range exactly; a
                # short stream with unreadable trailers must never pass.
                # Classification: when a cached stat shows the delivery
                # ended at EOF (server clamped a past-the-end range and
                # closed cleanly), every retry reproduces the clamp —
                # permanent, recorded as a hole without burning the gax
                # budget. Otherwise (mid-object truncation) transient.
                start = ranges[i][0]
                with self._stat_cache_lock:
                    size = self._stat_cache.get(name)
                if size is None:
                    # Bare read_ranges caller (no prior stat primed the
                    # cache): one stat here decides whether the short
                    # stream is a reproducible EOF clamp — worth a
                    # metadata RTT to avoid burning the whole gax budget
                    # re-fetching a clamp that reproduces every attempt.
                    try:
                        size = self.stat(name).size
                    except StorageError:
                        size = None  # can't classify: stay transient
                at_eof = size is not None and start + c["result"] >= size
                return StorageError(
                    f"ReadObject {name} range {i}: short stream "
                    f"({c['result']} of {length} bytes)"
                    + (" at EOF" if at_eof else ""),
                    transient=not at_eof,
                )
            return None

        # Setup failures classify onto every range (contract: this method
        # reports per-range outcomes, it doesn't throw for conditions the
        # threaded path would record as holes — and the caller's gax loop
        # can then heal transient ones, e.g. a token refresh hiccup).
        from tpubench.storage.native_pool import (
            fail_unfinished,
            run_multiplexed_batch,
        )

        try:
            pool = self._native_pool()  # raises when engine unavailable
            engine = pool.engine
            host, port, _ = self._native_endpoint()
            authority = f"{host}:{port}"
            metadata = self._native_auth_headers()
        except StorageError as e:
            return fail_unfinished(done, errs, e)
        except Exception as e:  # noqa: BLE001 — e.g. auth library errors
            return fail_unfinished(
                done, errs,
                StorageError(f"read_ranges setup: {e}", transient=True),
            )

        def submit(conn: int, i: int) -> None:
            start, length = ranges[i]
            engine.grpc_submit_to(
                conn, authority, self._bucket_path, name,
                addrs[i], length,
                read_offset=start, read_limit=length,
                headers=metadata, tag=i,
            )

        with self._tracer.span(
            "gcs_grpc.read_ranges", object=name, bucket=self.bucket,
            ranges=n,
        ):
            return run_multiplexed_batch(
                pool, n, done, errs, submit, classify, name,
                # An explicit grpc-status proves the server answered —
                # never retried as pool staleness.
                answered=lambda e: getattr(e, "grpc_status", -1) >= 0,
            )

    # ----------------------------------------------------------- backend --
    def open_read(self, name: str, start: int = 0, length: Optional[int] = None):
        if self.transport.native_receive:
            return self._open_read_native(name, start, length)
        if self._wire:
            wreq = wp.ReadObjectRequest(
                bucket=self._bucket_path,
                object=name,
                read_offset=start,
                read_limit=length or 0,
            )
            carrier = SpanCarrier(
                self._tracer, "gcs_grpc.read_object",
                object=name, bucket=self.bucket,
            )
            try:
                call = self._wire_chan().server_stream(
                    f"{_SVC}/ReadObject", wreq.encode()
                )
                flight_note("stream_open")
                return _WireGrpcReader(call, carrier=carrier)
            except BaseException as e:
                carrier.close(e)
                raise
        req = s2.ReadObjectRequest(
            bucket=self._bucket_path,
            object_=name,
            read_offset=start,
            read_limit=length or 0,
        )
        carrier = SpanCarrier(
            self._tracer, "gcs_grpc.read_object", object=name, bucket=self.bucket
        )
        try:
            stream = self._stub()["read"](req)
            flight_note("stream_open")
            return _GrpcReader(stream, carrier=carrier)
        except BaseException as e:
            carrier.close(e)
            if isinstance(e, grpc.RpcError):  # pragma: no cover - connect-time
                raise _wrap_rpc_error(e, f"ReadObject {name}") from e
            raise

    def _wire_write(self, name: str, data, if_generation_match) -> ObjectMeta:
        """One-shot WriteObject as a client-streaming wire call."""
        spec = wp.WriteObjectSpec(
            resource=wp.Object(name=name, bucket=self._bucket_path),
            if_generation_match=(
                int(if_generation_match)
                if if_generation_match is not None
                else None
            ),
        )
        mv = memoryview(data) if not isinstance(data, memoryview) else data
        call = self._wire_chan().bidi(f"{_SVC}/WriteObject")
        try:
            if not len(mv):
                call.send_message(
                    wp.WriteObjectRequest(
                        write_object_spec=spec, finish_write=True
                    ).encode(),
                    end=True,
                )
            else:
                off = 0
                first = True
                while off < len(mv):
                    chunk = mv[off : off + MAX_READ_CHUNK]
                    last = off + len(chunk) >= len(mv)
                    content = bytes(chunk)
                    call.send_message(
                        wp.WriteObjectRequest(
                            write_object_spec=spec if first else None,
                            write_offset=off,
                            checksummed_data=wp.ChecksummedData(
                                content=content,
                                crc32c=wp.crc32c_of(content),
                            ),
                            finish_write=last,
                        ).encode(),
                        end=last,
                    )
                    first = False
                    off += len(chunk)
            raw = call.recv_message()
            if raw is None:
                raise StorageError(
                    f"WriteObject {name}: no response message", transient=True
                )
            resp = wp.WriteObjectResponse.decode(raw)
            while call.recv_message() is not None:
                pass
        except BaseException:
            call.cancel()
            raise
        finally:
            call.close()
        res = resp.resource
        size = res.size if res is not None else resp.persisted_size
        with self._stat_cache_lock:
            self._stat_cache[name] = size
        return ObjectMeta(res.name if res is not None else name, size)

    def write(self, name: str, data: bytes,
              if_generation_match=None) -> ObjectMeta:
        if self._wire:
            return self._wire_write(name, data, if_generation_match)

        def requests():
            spec = s2.WriteObjectSpec(
                resource=s2.Object(name=name, bucket=self._bucket_path)
            )
            if if_generation_match is not None:
                spec.if_generation_match = int(if_generation_match)
            data_mv = memoryview(bytes(data))
            if not data_mv:
                yield s2.WriteObjectRequest(
                    write_object_spec=spec, write_offset=0, finish_write=True
                )
                return
            off = 0
            first = True
            while off < len(data_mv):
                chunk = data_mv[off : off + MAX_READ_CHUNK]
                last = off + len(chunk) >= len(data_mv)
                req = s2.WriteObjectRequest(
                    write_offset=off,
                    checksummed_data=s2.ChecksummedData(content=bytes(chunk)),
                    finish_write=last,
                )
                if first:
                    req.write_object_spec = spec
                    first = False
                off += len(chunk)
                yield req

        try:
            resp = self._stub()["write"](requests())
        except grpc.RpcError as e:
            raise _wrap_rpc_error(e, f"WriteObject {name}") from e
        # Keep the size cache coherent: a stale (smaller) cached size
        # would let the short-stream classifier call a genuine transient
        # truncation of the rewritten object "at EOF" and skip the retry.
        with self._stat_cache_lock:
            self._stat_cache[name] = int(resp.resource.size)
        return ObjectMeta(resp.resource.name, int(resp.resource.size))

    def open_write(self, name: str, if_generation_match=None):
        """Resumable session: StartResumableWrite → BidiWriteObject
        chunks with lockstep persisted-size acks → QueryWriteStatus
        re-probe on break → idempotent finalize (412 non-transient).
        The RetryingBackend wraps this in ``_ResumingWriter``, which
        drives the re-probe + tail-resend choreography."""
        if self._wire:
            return _WireBidiWriter(self, name, if_generation_match)
        return _LibBidiWriter(self, name, if_generation_match)

    def list(self, prefix: str = "", page_size: int = 0) -> list[ObjectMeta]:
        if self._wire:
            wreq = wp.ListObjectsRequest(
                parent=self._bucket_path, prefix=prefix,
                page_size=max(0, page_size),
            )
            raw = self._wire_unary(f"{_SVC}/ListObjects", wreq)
            resp = wp.ListObjectsResponse.decode(raw)
            return [
                ObjectMeta(o.name, o.size, o.generation)
                for o in resp.objects
            ]
        req = s2.ListObjectsRequest(parent=self._bucket_path, prefix=prefix)
        if page_size > 0:
            req.page_size = page_size
        try:
            resp = self._stub()["list"](req)
        except grpc.RpcError as e:
            raise _wrap_rpc_error(e, "ListObjects") from e
        return [
            ObjectMeta(o.name, int(o.size), int(o.generation)) for o in resp.objects
        ]

    def stat(self, name: str) -> ObjectMeta:
        if self._wire:
            raw = self._wire_unary(
                f"{_SVC}/GetObject",
                wp.GetObjectRequest(bucket=self._bucket_path, object=name),
            )
            o = wp.Object.decode(raw)
            with self._stat_cache_lock:
                self._stat_cache[name] = o.size
            return ObjectMeta(o.name, o.size, o.generation)
        req = s2.GetObjectRequest(bucket=self._bucket_path, object_=name)
        try:
            o = self._stub()["get"](req)
        except grpc.RpcError as e:
            raise _wrap_rpc_error(e, f"GetObject {name}") from e
        # Feed the size cache: read paths use it to bound ranged reads
        # and to classify an at-EOF short stream as permanent (a clamp
        # reproduces on every retry) instead of burning the gax budget.
        with self._stat_cache_lock:
            self._stat_cache[name] = int(o.size)
        return ObjectMeta(o.name, int(o.size), int(o.generation))

    def delete(self, name: str) -> None:
        if self._wire:
            self._wire_unary(
                f"{_SVC}/DeleteObject",
                wp.DeleteObjectRequest(bucket=self._bucket_path, object=name),
            )
            with self._stat_cache_lock:
                self._stat_cache.pop(name, None)
            return
        req = s2.DeleteObjectRequest(bucket=self._bucket_path, object_=name)
        try:
            self._stub()["delete"](req)
        except grpc.RpcError as e:
            raise _wrap_rpc_error(e, f"DeleteObject {name}") from e
        with self._stat_cache_lock:
            self._stat_cache.pop(name, None)

    def close(self) -> None:
        if self._owns_channels:
            for ch in self._channels:
                ch.close()
        if self._native_pool_obj is not None:
            self._native_pool_obj.close()  # also drains its BufferPool


class _WireBidiWriter:
    """Wire-mode resumable gRPC write (the ObjectWriter contract).

    StartResumableWrite issues the session; each ``write`` chunk rides
    a BidiWriteObject message with ``flush`` + ``state_lookup`` set and
    waits for the persisted-size ack in lockstep — ``offset`` is always
    the server's committed watermark, never an optimistic local count.
    A transient break tears the stream down and re-raises; the
    ``_ResumingWriter`` above re-probes ``committed()`` (QueryWriteStatus)
    and resends the tail on a fresh stream (first message re-carries the
    upload id). ``finalize`` sends ``finish_write`` and half-closes; a
    412 precondition verdict arrives non-transient."""

    def __init__(self, backend: GcsGrpcBackend, name: str,
                 if_generation_match=None):
        self._b = backend
        self.name = name
        spec = wp.WriteObjectSpec(
            resource=wp.Object(name=name, bucket=backend._bucket_path),
            if_generation_match=(
                int(if_generation_match)
                if if_generation_match is not None
                else None
            ),
        )
        raw = backend._wire_unary(
            f"{_SVC}/StartResumableWrite",
            wp.StartResumableWriteRequest(write_object_spec=spec),
        )
        self._uid = wp.StartResumableWriteResponse.decode(raw).upload_id
        self.offset = 0
        self._call = None
        self._fresh = True
        self._final: Optional[ObjectMeta] = None

    # ----------------------------------------------------------- stream --
    def _send(self, msg: "wp.BidiWriteObjectRequest", end: bool = False):
        if self._call is None:
            self._call = self._b._wire_chan().bidi(f"{_SVC}/BidiWriteObject")
            self._fresh = True
        if self._fresh:
            # The upload id rides only the FIRST message of each stream
            # (the storage-v2 first_message contract).
            msg.upload_id = self._uid
            self._fresh = False
        self._call.send_message(msg.encode(), end=end)
        return self._call

    def _break_stream(self) -> None:
        call, self._call = self._call, None
        if call is not None:
            call.cancel()

    # --------------------------------------------------------- contract --
    def write(self, data) -> int:
        mv = memoryview(data) if not isinstance(data, memoryview) else data
        off = 0
        try:
            while off < len(mv):
                chunk = mv[off : off + MAX_READ_CHUNK]
                content = bytes(chunk)
                call = self._send(
                    wp.BidiWriteObjectRequest(
                        write_offset=self.offset,
                        checksummed_data=wp.ChecksummedData(
                            content=content, crc32c=wp.crc32c_of(content)
                        ),
                        flush=True,
                        state_lookup=True,
                    )
                )
                raw = call.recv_message()
                if raw is None:
                    raise StorageError(
                        f"BidiWriteObject {self.name}: stream closed "
                        "before persisted-size ack",
                        transient=True,
                    )
                ack = wp.BidiWriteObjectResponse.decode(raw)
                annotate(
                    "bidi_ack", persisted=ack.persisted_size, object=self.name
                )
                self.offset = ack.persisted_size
                off += len(chunk)
        except StorageError:
            self._break_stream()
            raise
        return self.offset

    def committed(self) -> int:
        raw = self._b._wire_unary(
            f"{_SVC}/QueryWriteStatus",
            wp.QueryWriteStatusRequest(upload_id=self._uid),
        )
        resp = wp.QueryWriteStatusResponse.decode(raw)
        self.offset = resp.persisted_size
        return self.offset

    def finalize(self) -> ObjectMeta:
        if self._final is not None:
            return self._final
        try:
            call = self._send(
                wp.BidiWriteObjectRequest(
                    write_offset=self.offset, finish_write=True
                ),
                end=True,
            )
            raw = call.recv_message()
            if raw is None:
                raise StorageError(
                    f"BidiWriteObject {self.name}: no finalize response",
                    transient=True,
                )
            resp = wp.BidiWriteObjectResponse.decode(raw)
            while call.recv_message() is not None:
                pass
            call.close()
            self._call = None
        except StorageError:
            self._break_stream()
            raise
        res = resp.resource
        if res is not None:
            meta = ObjectMeta(res.name or self.name, res.size, res.generation)
        else:
            meta = ObjectMeta(self.name, resp.persisted_size)
        with self._b._stat_cache_lock:
            self._b._stat_cache[meta.name] = meta.size
        self._final = meta
        return meta

    def abort(self) -> None:
        try:
            self._break_stream()
        except Exception:
            pass


class _LibBidiWriter:
    """Library-mode twin of :class:`_WireBidiWriter`: the same RPC
    choreography over grpcio ``stream_stream`` with a queue-driven
    request iterator (lockstep: enqueue one request, pull one ack —
    ``state_lookup`` guarantees the server answers per chunk)."""

    def __init__(self, backend: GcsGrpcBackend, name: str,
                 if_generation_match=None):
        self._b = backend
        self.name = name
        spec = s2.WriteObjectSpec(
            resource=s2.Object(name=name, bucket=backend._bucket_path)
        )
        if if_generation_match is not None:
            spec.if_generation_match = int(if_generation_match)
        try:
            resp = backend._stub()["start_resumable"](
                s2.StartResumableWriteRequest(write_object_spec=spec)
            )
        except grpc.RpcError as e:
            raise _wrap_rpc_error(e, f"StartResumableWrite {name}") from e
        self._uid = resp.upload_id
        self.offset = 0
        self._q = None
        self._resp_iter = None
        self._fresh = True
        self._final: Optional[ObjectMeta] = None

    # ----------------------------------------------------------- stream --
    def _send(self, req, end: bool = False):
        if self._resp_iter is None:
            import queue as _queue

            q = _queue.Queue()

            def gen():
                while True:
                    item = q.get()
                    if item is None:
                        return
                    yield item

            self._q = q
            self._resp_iter = self._b._stub()["bidi_write"](gen())
            self._fresh = True
        if self._fresh:
            req.upload_id = self._uid
            self._fresh = False
        self._q.put(req)
        if end:
            self._q.put(None)
        return self._resp_iter

    def _break_stream(self) -> None:
        it, self._resp_iter = self._resp_iter, None
        q, self._q = self._q, None
        if q is not None:
            q.put(None)
        if it is not None:
            try:
                it.cancel()
            except Exception:
                pass

    def _recv(self, it, what: str):
        try:
            return next(it)
        except StopIteration:
            raise StorageError(
                f"{what}: stream closed before ack", transient=True
            ) from None
        except grpc.RpcError as e:
            raise _wrap_rpc_error(e, what) from e

    # --------------------------------------------------------- contract --
    def write(self, data) -> int:
        mv = memoryview(data) if not isinstance(data, memoryview) else data
        off = 0
        try:
            while off < len(mv):
                chunk = mv[off : off + MAX_READ_CHUNK]
                it = self._send(
                    s2.BidiWriteObjectRequest(
                        write_offset=self.offset,
                        checksummed_data=s2.ChecksummedData(
                            content=bytes(chunk)
                        ),
                        flush=True,
                        state_lookup=True,
                    )
                )
                ack = self._recv(it, f"BidiWriteObject {self.name}")
                annotate(
                    "bidi_ack",
                    persisted=int(ack.persisted_size),
                    object=self.name,
                )
                self.offset = int(ack.persisted_size)
                off += len(chunk)
        except StorageError:
            self._break_stream()
            raise
        return self.offset

    def committed(self) -> int:
        try:
            resp = self._b._stub()["query_write"](
                s2.QueryWriteStatusRequest(upload_id=self._uid)
            )
        except grpc.RpcError as e:
            raise _wrap_rpc_error(e, f"QueryWriteStatus {self.name}") from e
        self.offset = int(resp.persisted_size)
        return self.offset

    def finalize(self) -> ObjectMeta:
        if self._final is not None:
            return self._final
        try:
            it = self._send(
                s2.BidiWriteObjectRequest(
                    write_offset=self.offset, finish_write=True
                ),
                end=True,
            )
            resp = self._recv(it, f"BidiWriteObject {self.name} finalize")
            for _ in it:
                pass
            self._resp_iter = None
            self._q = None
        except StorageError:
            self._break_stream()
            raise
        meta = ObjectMeta(
            resp.resource.name or self.name,
            int(resp.resource.size),
            int(resp.resource.generation),
        )
        with self._b._stat_cache_lock:
            self._b._stat_cache[meta.name] = meta.size
        self._final = meta
        return meta

    def abort(self) -> None:
        try:
            self._break_stream()
        except Exception:
            pass


def _empty_deserializer(b: bytes):
    return b
