"""GCS JSON-API backend over pooled HTTP/1.1 connections.

Reference parity (``CreateHttpClient``, main.go:62-104):

* **HTTP/1.1 only** — the reference explicitly kills HTTP/2 by zeroing
  ``TLSNextProto`` because "http1 makes the client more performant"
  (main.go:64-72). Python's ``http.client`` is HTTP/1.1-native, so the
  performant path is the default here; ``http2=True`` is rejected loudly
  rather than silently downgraded.
* **Connection pool caps** — ``MaxConnsPerHost=100`` bounds total live
  connections (a semaphore), ``MaxIdleConnsPerHost=100`` bounds the idle
  keep-alive pool (main.go:31-32,66-68).
* **User-Agent middleware** — header injected on every request
  (``user_agent_round_tripper.go:22-30``).
* **Token source** — Authorization: Bearer from ``auth.py``
  (oauth2.Transport wrap, main.go:89-95).
* **Retry** — NOT here. The reference attaches retry at the client level
  (``client.SetRetry``, main.go:179-184); the uniform equivalent is
  :class:`tpubench.storage.retrying.RetryingBackend`, which wraps this
  backend (and every other) with gax-policy retry + mid-stream resume.
  This module raises classified ``StorageError``s (transient for 408/429/5xx
  and socket errors) and nothing more.

The reader streams the response body straight into the caller's granule
buffer via ``HTTPResponse.readinto`` — no intermediate bytes objects — and
stamps ``first_byte_ns`` when the first payload byte lands, the
time-to-first-byte observability the reference lacks.
"""

from __future__ import annotations

import http.client
import json
import ssl
import threading
import urllib.parse
from typing import Optional

import time

from tpubench.config import TransportConfig
from tpubench.obs.flight import note_phase as flight_note
from tpubench.obs.tracing import NoopTracer, SpanCarrier
from tpubench.storage.auth import TokenSource, make_token_source
from tpubench.storage.base import ObjectMeta, StorageError

DEFAULT_ENDPOINT = "https://storage.googleapis.com"

# Status codes the GCS client treats as transient (storage/invoke.go upstream
# semantics: 408, 429, 5xx).
_TRANSIENT = {408, 429, 500, 502, 503, 504}

_drain_tls = threading.local()


def _drain_scratch() -> bytearray:
    """Per-thread 64 KiB drain sink, allocated once. Response closes that
    drain small remainders (to keep the connection reusable) used to
    allocate a fresh bytearray per close — a guaranteed allocation on
    every partially-consumed response, paid on the pipeline's hot path.
    One worker thread drains one response at a time, so a thread-local
    scratch is race-free by construction."""
    buf = getattr(_drain_tls, "buf", None)
    if buf is None:
        buf = _drain_tls.buf = bytearray(65536)
    return buf


class _ConnectionPool:
    """Keep-alive pool with the reference's two caps (main.go:31-32)."""

    def __init__(self, host: str, port: int, scheme: str, transport: TransportConfig):
        self._host, self._port, self._scheme = host, port, scheme
        self._max_conns = threading.Semaphore(transport.max_conns_per_host)
        self._max_idle = transport.max_idle_conns_per_host
        self._idle: list[http.client.HTTPConnection] = []
        self._lock = threading.Lock()
        # Connection accounting (native-pool parity): lets tests assert
        # which pool a request actually rode (e.g. that http2=True never
        # opens an h1.1 connection).
        self.stats = {"connects": 0}
        self._ctx = None
        if scheme == "https":
            self._ctx = ssl.create_default_context(
                cafile=transport.tls_ca_file or None
            )
            if transport.tls_insecure_skip_verify:
                self._ctx.check_hostname = False
                self._ctx.verify_mode = ssl.CERT_NONE

    def _new_conn(self) -> http.client.HTTPConnection:
        with self._lock:
            self.stats["connects"] += 1
        flight_note("connect")  # flight-recorder phase (no-op off-op)
        if self._scheme == "https":
            return http.client.HTTPSConnection(
                self._host, self._port, context=self._ctx, timeout=60
            )
        return http.client.HTTPConnection(self._host, self._port, timeout=60)

    def acquire(self) -> http.client.HTTPConnection:
        self._max_conns.acquire()
        with self._lock:
            if self._idle:
                return self._idle.pop()
        return self._new_conn()

    def release(self, conn: http.client.HTTPConnection, reusable: bool) -> None:
        put_back = False
        if reusable:
            with self._lock:
                if len(self._idle) < self._max_idle:
                    self._idle.append(conn)
                    put_back = True
        if not put_back:
            try:
                conn.close()
            except Exception:
                pass
        self._max_conns.release()

    def close(self) -> None:
        with self._lock:
            for c in self._idle:
                try:
                    c.close()
                except Exception:
                    pass
            self._idle.clear()


class _HttpReader:
    """Streams one media response; returns its connection to the pool on
    close. EOF-complete responses are reusable (keep-alive); aborted ones are
    not.

    ``carrier`` (optional) is the client-internal request span (the
    OC-bridge analog, trace_exporter.go:49-52): it covers
    request→body-complete, gets a ``first_byte`` event when the first
    payload byte lands, and ends when the reader closes — with the error
    attached when the body failed, so failed reads export as failed spans.
    """

    def __init__(self, pool: _ConnectionPool, conn, resp, length: int,
                 carrier=None, generation: Optional[int] = None):
        self._pool = pool
        self._conn = conn
        self._resp = resp
        self._remaining = length
        self.first_byte_ns: Optional[int] = None
        # Served object's generation (x-goog-generation header), None when
        # the server didn't stamp one — cache-invalidation consumers treat
        # None as "unknown", never as "unchanged".
        self.generation = generation
        self._done = False
        self._carrier = carrier

    def readinto(self, buf: memoryview) -> int:
        if self._done or self._remaining == 0:
            return 0
        want = min(len(buf), self._remaining)
        try:
            n = self._resp.readinto(buf[:want])
        except (http.client.HTTPException, OSError) as e:
            self._done = True
            err = StorageError(f"mid-stream read failed: {e}", transient=True)
            if self._carrier is not None:
                self._carrier.close(err)
            raise err from e
        if n == 0:
            self._done = True
            if self._remaining > 0:
                err = StorageError(
                    f"short body: {self._remaining} bytes missing", transient=True
                )
                if self._carrier is not None:
                    self._carrier.close(err)
                raise err
            return 0
        if self.first_byte_ns is None:
            self.first_byte_ns = time.perf_counter_ns()
            if self._carrier is not None:
                self._carrier.event("first_byte")
        self._remaining -= n
        return n

    def close(self) -> None:
        if self._conn is None:
            return
        complete = self._remaining == 0
        if not complete:
            # Drain small remainders so the connection stays reusable
            # (reused per-thread scratch: no allocation per close).
            if 0 < self._remaining <= 1 << 20:
                sink = memoryview(_drain_scratch())
                try:
                    while self._resp.readinto(sink):
                        pass
                    complete = True
                except Exception:
                    complete = False
        self._pool.release(self._conn, reusable=complete)
        self._conn = None
        if self._carrier is not None:
            self._carrier.close()  # idempotent; failure paths closed it already


class _NativeStreamReader:
    """Streaming native receive (SURVEY §2.5.1): the C++ engine parsed the
    response headers (``tb_conn_get_begin``); each ``readinto`` here hands
    the caller's own memory — granule buffer or staging slot — to
    ``tb_conn_body_read``, which recv()s straight into it without the GIL.
    Same socket→destination streaming discipline as the Python client's
    ``readinto`` loop (main.go:140's granule streaming), with native header
    parse and CLOCK_MONOTONIC stamps; no full-body intermediate buffer and
    no completion copy.

    Holds its pooled connection until ``close()``: complete bodies return
    it keep-alive; abandoned/failed ones discard it (stream state unknown).
    """

    _DRAIN_CAP = 1 << 20  # parity with _HttpReader: drain small remainders

    def __init__(self, pool, conn: int, content_len: int, first_byte_ns: int,
                 carrier=None):
        # Bound once at construction (the engine module is necessarily
        # imported by now): the per-granule readinto must not pay import
        # machinery inside the very hot loop this path exists to win.
        from tpubench.native.engine import PERMANENT_CODES, NativeError

        self._permanent_codes = PERMANENT_CODES
        self._native_error = NativeError
        self._pool = pool
        self._conn: Optional[int] = conn
        self._content_len = content_len  # -1 = close-delimited
        self._consumed = 0
        self.first_byte_ns: Optional[int] = first_byte_ns or None
        self._done = False
        self._failed = False
        self._carrier = carrier

    def readinto(self, buf: memoryview) -> int:
        if self._done or self._conn is None:
            return 0
        try:
            n = self._pool.engine.conn_body_read(self._conn, buf, len(buf))
        except self._native_error as e:
            self._failed = True
            self._done = True
            err = StorageError(
                f"mid-stream native read failed: {e}",
                transient=e.code not in self._permanent_codes,
            )
            if self._carrier is not None:
                self._carrier.close(err)
            raise err from e
        if n == 0:
            self._done = True
            return 0
        if self.first_byte_ns is None:
            self.first_byte_ns = time.perf_counter_ns()
        self._consumed += n
        return n

    def close(self) -> None:
        if self._conn is None:
            return
        conn, self._conn = self._conn, None
        if self._failed:
            self._pool.discard(conn)
            return  # carrier already closed with the error
        engine = self._pool.engine
        try:
            if not self._done and self._content_len >= 0:
                # Drain small remainders so the connection stays reusable
                # (same policy as the Python reader above; reused
                # per-thread scratch, not a fresh 64 KiB per close).
                left = self._content_len - self._consumed
                if 0 < left <= self._DRAIN_CAP:
                    sink = _drain_scratch()
                    while engine.conn_body_read(conn, sink, len(sink)) > 0:
                        pass
            reusable = engine.conn_get_end(conn)
        except Exception:
            self._pool.discard(conn)
        else:
            self._pool.release(conn, reusable)
        if self._carrier is not None:
            self._carrier.close()  # idempotent


class _NativeBufReader:
    """Reader over a natively received body (SURVEY §2.5.1: the streaming
    receive ran in C++ straight into a pre-registered aligned buffer).

    The GET has already completed by construction time; ``first_byte_ns``
    is the C++-side CLOCK_MONOTONIC stamp of the first payload byte —
    directly comparable with ``time.perf_counter_ns()`` on Linux, and more
    precise than the Python-side stamp (no interpreter wakeup in between).
    ``readinto`` serves granule-sized slices from the buffer.
    """

    def __init__(self, buf, length: int, first_byte_ns: int, release=None):
        self._buf = buf
        self._len = length
        self._pos = 0
        self.first_byte_ns: Optional[int] = first_byte_ns
        # Buffer disposal: back to the backend's BufferPool when pooled
        # (a fresh posix_memalign per GET is an mmap storm), else freed.
        self._release = release

    def readinto(self, out: memoryview) -> int:
        n = min(len(out), self._len - self._pos)
        if n <= 0:
            return 0
        out[:n] = self._buf.view(self._len)[self._pos : self._pos + n]
        self._pos += n
        return n

    def close(self) -> None:
        if self._buf is not None:
            if self._release is not None:
                self._release(self._buf)
            else:
                self._buf.free()
            self._buf = None


def _committed_from_range(range_hdr: Optional[str]) -> int:
    """``Range: bytes=0-N`` on a 308 → N+1 committed bytes; absent = 0
    (nothing persisted yet — the empty-session probe's answer)."""
    if not range_hdr or not range_hdr.startswith("bytes=0-"):
        return 0
    try:
        return int(range_hdr[len("bytes=0-"):]) + 1
    except ValueError:
        return 0


class _ResumableHttpWriter:
    """One resumable-upload session over the JSON API (``uploadType=
    resumable``): POST opens the session (the URL rides ``Location``),
    parts PUT with ``Content-Range: bytes a-b/*`` and are acknowledged
    with **308 + the committed ``Range``**, ``committed()`` is the
    ``bytes */*`` resume probe, ``finalize()`` the ``bytes */total``
    completion. Raises classified :class:`StorageError`s and nothing
    more — resume/retry composes above (RetryingBackend's writer), the
    module contract the read path already follows."""

    def __init__(self, backend: "GcsHttpBackend", name: str,
                 if_generation_match: Optional[int]):
        self._b = backend
        self.name = name
        path = (
            f"/upload/storage/v1/b/"
            f"{urllib.parse.quote(backend.bucket, safe='')}/o"
            f"?uploadType=resumable&name={urllib.parse.quote(name, safe='')}"
        )
        if if_generation_match is not None:
            path += f"&ifGenerationMatch={if_generation_match}"
        conn, resp = backend._checked(
            "POST", path,
            headers={"Content-Type": "application/octet-stream"},
            ok=(200, 201),
        )
        try:
            loc = resp.headers.get("Location", "")
            resp.read()
        finally:
            backend._pool.release(conn, reusable=True)
        if not loc:
            raise StorageError(
                f"resumable open {name}: server sent no session Location",
                transient=False,
            )
        u = urllib.parse.urlsplit(loc)
        self._session = u.path + (f"?{u.query}" if u.query else "")
        self.offset = 0
        self._final: Optional[ObjectMeta] = None

    def _put(self, content_range: str, body=b"", ok=(200, 201, 308)):
        conn, resp = self._b._request(
            "PUT", self._session,
            {"Content-Range": content_range,
             "Content-Type": "application/octet-stream"},
            body,
        )
        status = resp.status
        try:
            payload = resp.read()
        except (http.client.HTTPException, OSError) as e:
            self._b._pool.release(conn, reusable=False)
            raise StorageError(
                f"upload {self.name}: response died: {e}", transient=True
            ) from e
        self._b._pool.release(conn, reusable=True)
        if status not in ok:
            raise StorageError(
                f"upload {self.name} -> {status}: "
                f"{payload[:200].decode('utf-8', 'replace')}",
                transient=status in _TRANSIENT,
                code=status,
            )
        return status, resp.headers, payload

    def _finish(self, payload: bytes) -> ObjectMeta:
        meta = json.loads(payload)
        self._final = ObjectMeta(
            meta["name"], int(meta["size"]), int(meta.get("generation", 0))
        )
        self.offset = self._final.size
        return self._final

    def write(self, data) -> int:
        n = len(data)
        if n == 0:
            return self.offset
        start = self.offset
        status, headers, payload = self._put(
            f"bytes {start}-{start + n - 1}/*", bytes(data)
        )
        if status != 308:
            # Server finalized (an idempotent replay against a completed
            # session answers the object meta).
            self._finish(payload)
            return self.offset
        committed = _committed_from_range(headers.get("Range"))
        self.offset = committed
        if committed < start + n:
            # The server persisted a prefix: transient — the resuming
            # layer re-probes and resends the tail.
            raise StorageError(
                f"upload {self.name}: committed {committed} < sent "
                f"{start + n}", transient=True,
            )
        return committed

    def committed(self) -> int:
        if self._final is not None:
            return self.offset
        status, headers, payload = self._put("bytes */*")
        if status != 308:
            self._finish(payload)
        else:
            self.offset = _committed_from_range(headers.get("Range"))
        return self.offset

    def finalize(self) -> ObjectMeta:
        if self._final is not None:
            return self._final
        _status, _headers, payload = self._put(
            f"bytes */{self.offset}", ok=(200, 201)
        )
        return self._finish(payload)

    def abort(self) -> None:
        try:
            conn, resp = self._b._request("DELETE", self._session)
            try:
                resp.read()
            finally:
                self._b._pool.release(conn, reusable=True)
        except Exception:  # noqa: BLE001 — best-effort by contract
            pass


class GcsHttpBackend:
    """Thread-safe JSON-API client; one instance shared by all workers
    (reference shares one ``*storage.Client``, main.go:200-203)."""

    def __init__(
        self,
        bucket: str,
        transport: Optional[TransportConfig] = None,
        token_source: Optional[TokenSource] = None,
        tracer=None,
    ):
        self.bucket = bucket
        self.transport = transport or TransportConfig()
        # Client-internal spans (the reference's OC-bridge capability,
        # trace_exporter.go:49-52): per-request spans nest under the
        # workload's ReadObject span when the tracer propagates context
        # (OTel); NoopTracer costs nothing.
        self._tracer = tracer or NoopTracer()
        # http2=True: ALL GETs — media and metadata (stat/list) — ride
        # the native h2 client (engine.cc's frame/HPACK machinery;
        # Python's http.client cannot speak h2), reproducing the
        # reference's WHOLE-CLIENT HTTP/2 branch (ForceAttemptHTTP2,
        # main.go:76-80) so the "http1 is more performant" claim
        # (main.go:64) is measurable on the full read path. The h2
        # client is GET-only; write/delete stay on the HTTP/1.1 pool
        # (the reference's hot path issues no writes, main.go:121-148).
        self._h2_pool_obj = None
        self._h2_pool_lock = threading.Lock()
        self._h2_stat_cache: dict[str, int] = {}
        endpoint = self.transport.endpoint or DEFAULT_ENDPOINT
        u = urllib.parse.urlsplit(endpoint)
        self._scheme = u.scheme
        self._host = u.hostname or "storage.googleapis.com"
        self._port = u.port or (443 if self._scheme == "https" else 80)
        self._pool = _ConnectionPool(self._host, self._port, self._scheme, self.transport)
        self._tokens = token_source or make_token_source(
            self.transport.key_file, self.transport.endpoint
        )
        # Keep-alive pool for the native receive path (same connection
        # discipline as the Python client's pool, so A/Bs isolate the
        # receive loop): shared pool machinery, lazily built on first use
        # (locked: worker threads hit first use concurrently).
        self._native_pool_obj = None
        self._native_pool_lock = threading.Lock()

    @property
    def scheme(self) -> str:
        return self._scheme

    def native_request_parts(self, name: str) -> tuple:
        """(host, port, path, header-block) for a native-engine GET of
        ``name`` — request construction lives here once, shared by the
        backend's own native receive path and the fetch executor. Called
        per request so bearer tokens stay fresh."""
        headers = "".join(
            f"{k}: {v}\r\n"
            for k, v in self._headers().items()
            if k.lower() != "host"  # the engine sets Host itself
        )
        return self._host, self._port, self._opath(name) + "?alt=media", headers

    # ------------------------------------------------------- native pool --
    def _native_pool(self):
        with self._native_pool_lock:
            if self._native_pool_obj is None:
                from tpubench.storage.native_pool import build_native_pool

                self._native_pool_obj = build_native_pool(
                    self.transport, self._host, self._port,
                    tls=self._scheme == "https",
                )
        return self._native_pool_obj

    @property
    def _native_idle(self) -> list[int]:
        return self._native_pool().idle

    @property
    def native_conn_stats(self) -> dict:
        return self._native_pool().stats

    # ------------------------------------------------------------ request --
    def _headers(self) -> dict[str, str]:
        h = {
            # user_agent_round_tripper.go:22-30 (value from config, not "prince")
            "User-Agent": self.transport.user_agent,
            "Host": f"{self._host}:{self._port}",
        }
        tok = self._tokens.token()
        if tok:
            h["Authorization"] = f"Bearer {tok}"
        return h

    def _request(
        self, method: str, path: str, headers: Optional[dict] = None, body: bytes = b""
    ):
        """One attempt: acquire conn, send, return (conn, resp). Caller owns
        release."""
        conn = self._pool.acquire()
        try:
            h = self._headers()
            if headers:
                h.update(headers)
            conn.request(method, path, body=body or None, headers=h)
            resp = conn.getresponse()
            return conn, resp
        except (http.client.HTTPException, OSError) as e:
            self._pool.release(conn, reusable=False)
            raise StorageError(f"{method} {path}: {e}", transient=True) from e

    def _checked(self, method: str, path: str, headers=None, body=b"", ok=(200, 206)):
        conn, resp = self._request(method, path, headers, body)
        if resp.status in ok:
            return conn, resp
        try:
            payload = resp.read()
        except Exception:
            payload = b""
        finally:
            self._pool.release(conn, reusable=True)
        msg = payload[:200].decode("utf-8", "replace")
        raise StorageError(
            f"{method} {path} -> {resp.status}: {msg}",
            transient=resp.status in _TRANSIENT,
            code=resp.status,
        )

    # ------------------------------------------------------------ backend --
    def _opath(self, name: str) -> str:
        return (
            f"/storage/v1/b/{urllib.parse.quote(self.bucket, safe='')}"
            f"/o/{urllib.parse.quote(name, safe='')}"
        )

    def _h2_pool(self):
        with self._h2_pool_lock:
            if self._h2_pool_obj is None:
                from tpubench.storage.native_pool import build_native_pool

                # https: TLS with ALPN h2 required; plain http: h2c with
                # prior knowledge (what an h2-capable test server speaks).
                self._h2_pool_obj = build_native_pool(
                    self.transport, self._host, self._port,
                    tls=self._scheme == "https",
                    alpn_h2=self._scheme == "https",
                )
        return self._h2_pool_obj

    def _meta_get_h2(self, path: str, what: str) -> bytes:
        """Metadata GET over the native HTTP/2 client: under ``http2=True``
        the WHOLE read path rides h2 — stat and media alike — matching the
        reference's whole-client branch (``ForceAttemptHTTP2``,
        main.go:76-80) instead of isolating half the A/B. (The native h2
        client is GET-only, so write/delete stay on the HTTP/1.1 pool;
        the reference's hot path issues no writes, main.go:121-148.)
        Returns the response body bytes; raises classified StorageError."""
        from tpubench.native.engine import TB_ETOOBIG, PERMANENT_CODES, NativeError

        pool = self._h2_pool()
        engine = pool.engine
        headers = "".join(
            f"{k}: {v}\r\n"
            for k, v in self._headers().items()
            if k.lower() != "host"
        )
        authority = f"{self._host}:{self._port}"
        # Metadata bodies are usually tiny, but a big bucket's list JSON
        # can run to megabytes (several hundred bytes per object): grow
        # the buffer on TB_ETOOBIG rather than failing permanently where
        # the h1.1 path would have succeeded.
        for cap in (256 * 1024, 16 * 1024 * 1024):
            buf = pool.buffers.acquire(cap)

            def do_request(conn: int) -> dict:
                with self._tracer.span(
                    "gcs_http.meta_h2", path=path, bucket=self.bucket
                ) as sp:
                    engine.h2_submit_get(
                        conn, authority, path, buf, headers=headers
                    )
                    c = engine.h2_poll(conn)
                    if c is None:
                        raise NativeError("h2 stream vanished", code=-1001)
                    sp.event("first_byte", native_ns=c["first_byte_ns"])
                return c

            try:
                r = pool.run(do_request)
            except StorageError:
                pool.buffers.release(buf)
                raise
            except NativeError as e:
                pool.buffers.release(buf)
                raise StorageError(
                    f"h2 {what}: {e}", transient=e.code not in PERMANENT_CODES
                ) from e
            except BaseException:
                pool.buffers.release(buf)
                raise
            status = r["http_status"]
            if r["result"] == TB_ETOOBIG and cap == 256 * 1024:
                pool.buffers.release(buf)
                continue  # body outgrew the small buffer: one big retry
            if r["result"] < 0:
                pool.buffers.release(buf)
                raise StorageError(
                    f"h2 {what}: stream error {r['result']} (status {status})",
                    transient=r["result"] not in PERMANENT_CODES,
                )
            body = bytes(buf.view(r["result"]))
            pool.buffers.release(buf)
            if status != 200:
                raise StorageError(
                    f"h2 {what} -> {status}: "
                    f"{body[:200].decode('utf-8', 'replace')}",
                    transient=status in _TRANSIENT,
                    code=status,
                )
            return body
        raise StorageError(  # pragma: no cover — loop always returns/raises
            f"h2 {what}: body exceeded 16 MiB metadata buffer", transient=False
        )

    def _open_read_h2(self, name: str, start: int, length: Optional[int]):
        """Media GET over the native HTTP/2 client. The response body
        (DATA frames) lands directly in an aligned buffer sized from the
        requested range (or object metadata); :status arrives via HPACK.
        Multiplexing note: each pooled connection CAN carry 32 concurrent
        streams (tb_grpc_submit/tb_grpc_poll) — this sequential reader
        uses one at a time, matching the HTTP/1.1 path's per-request
        discipline so the h1-vs-h2 A/B isolates the protocol."""
        from tpubench.native.engine import PERMANENT_CODES, NativeError

        pool = self._h2_pool()
        engine = pool.engine
        if length is None:
            with self._h2_pool_lock:
                size = self._h2_stat_cache.get(name)
            if size is None:
                size = self.stat(name).size
                with self._h2_pool_lock:
                    self._h2_stat_cache[name] = size
            want = size - start
        else:
            want = length
        _, _, req_path, headers = self.native_request_parts(name)
        if start or length is not None:
            end = "" if length is None else str(start + want - 1)
            headers += f"Range: bytes={start}-{end}\r\n"
        authority = f"{self._host}:{self._port}"
        buf = pool.buffers.acquire(max(4096, want))

        def do_request(conn: int) -> dict:
            with self._tracer.span(
                "gcs_http.get_h2", object=name, bucket=self.bucket
            ) as sp:
                engine.h2_submit_get(
                    conn, authority, req_path, buf, headers=headers
                )
                flight_note("stream_open")
                c = engine.h2_poll(conn)
                if c is None:
                    raise NativeError("h2 stream vanished", code=-1001)
                sp.event("first_byte", native_ns=c["first_byte_ns"])
            return c

        try:
            r = pool.run(do_request)
        except StorageError:
            pool.buffers.release(buf)  # connect failure, classified
            raise
        except NativeError as e:
            pool.buffers.release(buf)
            with self._h2_pool_lock:
                self._h2_stat_cache.pop(name, None)
            raise StorageError(
                f"h2 GET {name}: {e}",
                transient=e.code not in PERMANENT_CODES,
            ) from e
        except BaseException:
            pool.buffers.release(buf)
            raise
        status = r["http_status"]
        if r["result"] < 0:
            # Per-stream failure: the connection survived (it went back to
            # the pool); classify the stream's code. One carve-out, same
            # as the round-2 native path: body-exceeds-buffer when the
            # buffer was sized from the (just-invalidated) stat cache —
            # the object may have grown, and one retry re-stats.
            from tpubench.native.engine import TB_ETOOBIG

            pool.buffers.release(buf)
            with self._h2_pool_lock:
                self._h2_stat_cache.pop(name, None)
            transient = r["result"] not in PERMANENT_CODES
            if r["result"] == TB_ETOOBIG and length is None:
                transient = True
            raise StorageError(
                f"h2 GET {name}: stream error {r['result']} "
                f"(status {status})",
                transient=transient,
            )
        if status not in (200, 206):
            msg = bytes(buf.view(min(r["result"], 200))).decode(
                "utf-8", "replace"
            )
            pool.buffers.release(buf)
            raise StorageError(
                f"h2 GET {name}: HTTP {status}: {msg}",
                transient=status in _TRANSIENT,
                code=status,
            )
        if (start > 0 and status == 200) or (
            length is not None and r["result"] > want
        ):
            # Server ignored the Range: 200 to a nonzero-start request
            # (bytes would be misaligned), or more bytes than the bounded
            # range asked for — same protocol-shape rule as the h1 path.
            pool.buffers.release(buf)
            raise StorageError(
                f"h2 GET {name}: server ignored Range "
                f"(status {status}, got {r['result']}, asked {want})",
                transient=False,
            )
        return _NativeBufReader(
            buf, r["result"], r["first_byte_ns"], release=pool.buffers.release
        )

    def read_ranges(self, name: str, ranges, buffers) -> list:
        """Concurrent ranged GETs multiplexed on ONE native h2 connection
        (up to 32 streams — the h2 twin of ``GcsGrpcBackend.read_ranges``,
        same per-range contract): range *i* (``(start, length)``) lands in
        ``buffers[i]``; returns per-range ``None`` or a classified
        :class:`StorageError`. Per-stream failures touch only their range;
        connection-fatal failures classify onto every unfinished range;
        one whole-batch retransmit when the first use of a pooled
        connection fails before any completion. Requires
        ``transport.http2`` (the reference's whole-client h2 branch is
        where multiplexing exists, main.go:76-80)."""
        import numpy as np

        from tpubench.native.engine import PERMANENT_CODES

        if not self.transport.http2:
            raise ValueError("read_ranges requires transport.http2")
        n = len(ranges)
        done: list[bool] = [False] * n
        errs: list = [None] * n
        addrs: list[int] = []
        for i, ((start, length), b) in enumerate(zip(ranges, buffers)):
            arr = b if isinstance(b, np.ndarray) else np.frombuffer(b, np.uint8)
            if not (arr.flags.writeable and arr.flags.c_contiguous):
                raise ValueError(
                    f"range {i}: buffer must be writable and C-contiguous"
                )
            if arr.nbytes < length:
                raise ValueError(
                    f"range {i}: buffer {arr.nbytes} < length {length}"
                )
            addrs.append(arr.ctypes.data)
            if length == 0:
                done[i] = True
        if all(done):
            return errs

        def classify(i: int, c: dict):
            length = ranges[i][1]
            status = c["http_status"]
            if c["result"] < 0:
                return StorageError(
                    f"h2 GET {name} range {i}: stream error {c['result']} "
                    f"(status {status})",
                    transient=c["result"] not in PERMANENT_CODES,
                )
            if status not in (200, 206):
                return StorageError(
                    f"h2 GET {name} range {i}: HTTP {status}",
                    transient=status in _TRANSIENT,
                    code=status,
                )
            if status == 200 and ranges[i][0] > 0:
                # Server ignored the Range: bytes would be misaligned.
                return StorageError(
                    f"h2 GET {name} range {i}: server ignored Range",
                    transient=False,
                )
            if c["result"] != length:
                # Same EOF-clamp discipline as the gRPC twin: a short
                # delivery ending at the known object size reproduces on
                # every retry — permanent; stat inline on a cache miss.
                start = ranges[i][0]
                with self._h2_pool_lock:
                    size = self._h2_stat_cache.get(name)
                if size is None:
                    try:
                        size = self.stat(name).size
                        with self._h2_pool_lock:
                            self._h2_stat_cache[name] = size
                    except StorageError:
                        size = None
                at_eof = size is not None and start + c["result"] >= size
                return StorageError(
                    f"h2 GET {name} range {i}: short stream "
                    f"({c['result']} of {length} bytes)"
                    + (" at EOF" if at_eof else ""),
                    transient=not at_eof,
                )
            return None

        from tpubench.storage.native_pool import (
            fail_unfinished,
            run_multiplexed_batch,
        )

        try:
            pool = self._h2_pool()
            engine = pool.engine
            _, _, req_path, base_headers = self.native_request_parts(name)
            authority = f"{self._host}:{self._port}"
        except StorageError as e:
            return fail_unfinished(done, errs, e)
        except Exception as e:  # noqa: BLE001 — e.g. auth library errors
            return fail_unfinished(
                done, errs,
                StorageError(f"read_ranges setup: {e}", transient=True),
            )

        def submit(conn: int, i: int) -> None:
            start, length = ranges[i]
            hdrs = (
                base_headers
                + f"Range: bytes={start}-{start + length - 1}\r\n"
            )
            engine.h2_submit_get_to(
                conn, authority, req_path, addrs[i], length,
                headers=hdrs, tag=i,
            )

        with self._tracer.span(
            "gcs_http.read_ranges_h2", object=name, bucket=self.bucket,
            ranges=n,
        ):
            return run_multiplexed_batch(
                pool, n, done, errs, submit, classify, name
            )

    def open_read(self, name: str, start: int = 0, length: Optional[int] = None):
        if self.transport.http2:
            return self._open_read_h2(name, start, length)
        if self.transport.native_receive:
            return self._open_read_native(name, start, length)
        headers = {}
        if start or length is not None:
            end = "" if length is None else str(start + length - 1)
            headers["Range"] = f"bytes={start}-{end}"
        # Request span spanning request→body-complete: the reader owns its
        # end (close()), mirroring the library-internal spans the reference
        # gets from the OC bridge. Everything between enter and reader
        # construction stays inside the guard — a leaked entered span would
        # corrupt the thread's OTel context for the rest of the run.
        carrier = SpanCarrier(
            self._tracer, "gcs_http.get", object=name, bucket=self.bucket
        )
        try:
            conn, resp = self._checked(
                "GET", self._opath(name) + "?alt=media", headers=headers
            )
            flight_note("stream_open")
            carrier.event("response_headers", status=resp.status)
            clen = int(resp.headers.get("Content-Length", "0"))
            gen_hdr = resp.headers.get("x-goog-generation")
            return _HttpReader(
                self._pool, conn, resp, clen, carrier=carrier,
                generation=int(gen_hdr) if gen_hdr else None,
            )
        except BaseException as e:
            carrier.close(e)
            raise

    def _open_read_native(self, name: str, start: int, length: Optional[int]):
        """Opt-in C++ receive path (``transport.native_receive``): the
        engine sends the GET and parses the response headers
        (``tb_conn_get_begin``); body bytes then stream from the socket
        DIRECTLY into whatever memory the caller's ``readinto`` offers —
        granule buffer or staging slot — with no full-body intermediate
        buffer and no completion copy (the round-2 path landed the whole
        body in a pool buffer first, which cost it the A/B against the
        Python client). Pooled keep-alive connections, same discipline as
        the Python path; https rides the engine's TLS layer (verification
        against ``transport.tls_ca_file`` or the system store;
        ``transport.tls_insecure_skip_verify`` for self-signed test
        endpoints)."""
        from tpubench.native.engine import PERMANENT_CODES, NativeError

        pool = self._native_pool()  # raises when engine/TLS unavailable
        engine = pool.engine
        _, _, req_path, headers = self.native_request_parts(name)
        if length is not None:
            headers += f"Range: bytes={start}-{start + length - 1}\r\n"
        elif start:
            headers += f"Range: bytes={start}-\r\n"
        # A stale pooled socket (server timed it out, or trailing junk
        # arrived after the reuse-time drain check) fails at begin() on
        # first use — standard HTTP-client behavior is one immediate
        # retransmit of the idempotent GET on a FRESH socket, so pool
        # staleness never surfaces as a request failure. Permanent
        # protocol-shape codes never burn the retransmit (they reproduce).
        conn, reused = pool.acquire()
        carrier = SpanCarrier(
            self._tracer, "gcs_http.get_native", object=name, bucket=self.bucket
        )
        # Flight stream_open BEFORE begin(): begin() reads the response
        # headers and stamps the native first_byte — noting afterwards
        # would order stream_open after first_byte and break the
        # journal's monotonicity invariant (first-stamp-wins makes this
        # safe across the stale retransmit below).
        flight_note("stream_open")
        while True:
            try:
                r = engine.conn_get_begin(
                    conn, self._host, self._port, req_path, headers=headers
                )
                break
            except NativeError as e:
                pool.discard(conn)
                if reused and e.code not in PERMANENT_CODES:
                    reused = False
                    pool.note_stale_retry()  # also flight-annotates
                    carrier.event("stale_retry")
                    try:
                        conn = pool.fresh()
                    except BaseException as e2:
                        carrier.close(e2)
                        raise
                    continue
                # Module contract: this layer raises classified
                # StorageErrors, on the engine's code ABI — socket-level
                # failures transient, protocol-shape failures permanent.
                err = StorageError(
                    f"native GET {name}: {e}",
                    transient=e.code not in PERMANENT_CODES,
                )
                carrier.close(err)
                raise err from e
            except BaseException as e:
                # Includes KeyboardInterrupt: never strand the connection.
                pool.discard(conn)
                carrier.close(e)
                raise
        carrier.event("response_headers", status=r["status"])
        if r["first_byte_ns"]:
            # Begin() read the response headers, so the native first-byte
            # stamp exists by now — surface it on the span like the Python
            # reader's first_byte event (trace symmetry for A/Bs).
            carrier.event("first_byte", native_ns=r["first_byte_ns"])
        range_ignored = r["status"] in (200, 206) and (
            # Too many bytes announced for a bounded range.
            (
                length is not None
                and r["content_len"] >= 0
                and r["content_len"] > length
            )
            # Any range from a nonzero start answered with 200: the body
            # starts at offset 0, not `start` — serving it would silently
            # hand back the wrong bytes (the round-2 buffer path caught
            # this as TB_ETOOBIG; streaming has no buffer, so the check
            # lives here). A conformant server honoring any Range answers
            # 206.
            or (start > 0 and r["status"] == 200)
        )
        if range_ignored:
            # Protocol-shape failure — a retry reproduces it. Fail loudly
            # rather than silently serving bytes the caller never asked
            # for.
            pool.discard(conn)
            err = StorageError(
                f"GET {name}: server ignored Range "
                f"(status {r['status']}, announced {r['content_len']}, "
                f"requested start={start} length={length})",
                transient=False,
            )
            carrier.close(err)
            raise err
        if r["status"] not in (200, 206):
            # Error payload: read the message head, then drain the rest
            # ONLY when it is small and bounded (same _DRAIN_CAP rule as
            # the reader's close()) — a hostile/huge error body must not
            # stall the worker; discarding the connection is cheaper.
            msg = bytearray(4096)
            n = 0
            try:
                n = engine.conn_body_read(conn, msg, len(msg))
                clen = r["content_len"]
                if 0 <= clen <= _NativeStreamReader._DRAIN_CAP:
                    sink = _drain_scratch()
                    while engine.conn_body_read(conn, sink, len(sink)) > 0:
                        pass
                    pool.release(conn, engine.conn_get_end(conn))
                else:
                    pool.discard(conn)
            except Exception:
                pool.discard(conn)
            err = StorageError(
                f"GET {name}: HTTP {r['status']}: "
                f"{msg[:n].decode('utf-8', 'replace')[:200]}",
                transient=r["status"] in _TRANSIENT,
                code=r["status"],
            )
            carrier.close(err)
            raise err
        return _NativeStreamReader(
            pool, conn, r["content_len"], r["first_byte_ns"], carrier=carrier
        )

    def write(self, name: str, data: bytes,
              if_generation_match: Optional[int] = None) -> ObjectMeta:
        with self._h2_pool_lock:
            self._h2_stat_cache.pop(name, None)  # size changes on write
        path = (
            f"/upload/storage/v1/b/{urllib.parse.quote(self.bucket, safe='')}/o"
            f"?uploadType=media&name={urllib.parse.quote(name, safe='')}"
        )
        if if_generation_match is not None:
            path += f"&ifGenerationMatch={if_generation_match}"
        conn, resp = self._checked(
            "POST",
            path,
            headers={"Content-Type": "application/octet-stream"},
            body=bytes(data),
        )
        try:
            meta = json.loads(resp.read())
        finally:
            self._pool.release(conn, reusable=True)
        return ObjectMeta(
            meta["name"], int(meta["size"]), int(meta.get("generation", 0))
        )

    def open_write(self, name: str,
                   if_generation_match: Optional[int] = None):
        """Resumable multi-part upload session (the GCS
        ``uploadType=resumable`` protocol): POST opens the session, each
        part PUTs with ``Content-Range: bytes a-b/*`` and a 308-with-
        ``Range`` acknowledgement, ``finalize`` PUTs the ``bytes */total``
        completion form. Part-level retry/resume is NOT here — the
        uniform equivalent is :class:`RetryingBackend.open_write`'s
        resuming wrapper (the read path's resume discipline, mirrored)."""
        return _ResumableHttpWriter(self, name, if_generation_match)

    def list(self, prefix: str = "", page_size: int = 0) -> list[ObjectMeta]:
        """Full listing under ``prefix``, following ``nextPageToken``
        pages. ``page_size`` > 0 rides as ``maxResults`` (the wire page
        bound meta-storm exercises); the client always drains every
        page, so callers see one complete listing either way."""
        base = (
            f"/storage/v1/b/{urllib.parse.quote(self.bucket, safe='')}/o"
            f"?prefix={urllib.parse.quote(prefix, safe='')}"
        )
        if page_size > 0:
            base += f"&maxResults={page_size}"
        out: list[ObjectMeta] = []
        token = ""
        while True:
            path = base
            if token:
                path += f"&pageToken={urllib.parse.quote(token, safe='')}"
            if self.transport.http2:
                payload = json.loads(
                    self._meta_get_h2(path, f"LIST {prefix!r}")
                )
            else:
                conn, resp = self._checked("GET", path)
                try:
                    payload = json.loads(resp.read())
                finally:
                    self._pool.release(conn, reusable=True)
            out.extend(
                ObjectMeta(
                    it["name"], int(it["size"]), int(it.get("generation", 0))
                )
                for it in payload.get("items", [])
            )
            token = payload.get("nextPageToken", "")
            if not token:
                return out

    def stat(self, name: str) -> ObjectMeta:
        if self.transport.http2:
            meta = json.loads(
                self._meta_get_h2(self._opath(name), f"STAT {name}")
            )
        else:
            conn, resp = self._checked("GET", self._opath(name))
            try:
                meta = json.loads(resp.read())
            finally:
                self._pool.release(conn, reusable=True)
        return ObjectMeta(
            meta["name"], int(meta["size"]), int(meta.get("generation", 0))
        )

    def delete(self, name: str) -> None:
        with self._h2_pool_lock:
            self._h2_stat_cache.pop(name, None)
        conn, resp = self._checked("DELETE", self._opath(name), ok=(200, 204))
        try:
            resp.read()
        finally:
            self._pool.release(conn, reusable=True)

    def close(self) -> None:
        self._pool.close()
        if self._native_pool_obj is not None:
            self._native_pool_obj.close()  # also drains its BufferPool
        if self._h2_pool_obj is not None:
            self._h2_pool_obj.close()
