"""Dependency-free gRPC-over-HTTP/2 wire stack for storage v2.

The hermetic transport plane ROADMAP item 1 asks for: a hand-rolled
protobuf codec (:mod:`proto`) for the handful of storage-v2 messages
tpubench speaks, gRPC message framing + status mapping (:mod:`framing`),
and a client connection (:mod:`client`) that runs those frames over a
plain socket (h2c prior knowledge) or TLS+ALPN h2 — no ``grpcio``, no
gapic types. :class:`~tpubench.storage.gcs_grpc.GcsGrpcBackend` rides
this stack whenever the real libraries are absent, against the
:class:`~tpubench.storage.fake_grpc_wire_server.FakeGrpcWireServer`
twin that serves the same frames from the shared :class:`FakeBackend`.
"""

from tpubench.storage.grpc_wire.framing import (  # noqa: F401
    FrameDecoder,
    WireCodecError,
    encode_frame,
    status_to_storage_error,
    storage_error_to_status,
)
from tpubench.storage.grpc_wire.client import (  # noqa: F401
    GrpcWireChannel,
)
