"""gRPC-over-HTTP/2 client connection — no grpcio, no h2 package.

Speaks exactly the frame subset the RPC shapes need: client preface +
SETTINGS, HEADERS with literal-never-indexed HPACK (the same encoding
the native engine's client emits), DATA carrying 5-byte-prefixed gRPC
messages, trailers HEADERS carrying ``grpc-status``. One RPC at a time
per connection — :class:`GrpcWireChannel` keeps a small free-list and
dials extra sockets under concurrency, which is also how the reference
Go client's ``WithGRPCConnectionPool`` behaves (N independent
subchannels, calls round-robined across them).

Failure classification matches the library-mode tables in
``gcs_grpc``: socket EOF / RST_STREAM / GOAWAY mid-RPC are transient
(UNAVAILABLE-shaped), a blown per-read deadline is transient
(DEADLINE_EXCEEDED-shaped), and a missing ``grpc-status`` after
END_STREAM is a transient protocol error — the retry planes above
never see a raw ``OSError``.
"""

from __future__ import annotations

import socket
import ssl
import struct
import threading
import time
from typing import Optional

from tpubench.obs.flight import annotate
from tpubench.storage.base import StorageError
from tpubench.storage.fake_h2_server import (
    _PREFACE,
    _HpackError,
    _hp_literal,
    decode_request_headers,
)
from tpubench.storage.grpc_wire.framing import (
    OK,
    FrameDecoder,
    WireCodecError,
    encode_frame,
    status_to_storage_error,
)

# Frame types (RFC 9113 §6).
_DATA = 0x0
_HEADERS = 0x1
_RST_STREAM = 0x3
_SETTINGS = 0x4
_PING = 0x6
_GOAWAY = 0x7
_WINDOW_UPDATE = 0x8

_FLAG_END_STREAM = 0x1
_FLAG_ACK = 0x1
_FLAG_END_HEADERS = 0x4
_FLAG_PADDED = 0x8
_FLAG_PRIORITY = 0x20

# SETTINGS we advertise: effectively-unbounded stream window plus the
# legal max frame size, so servers that DO enforce flow control (a real
# grpcio server, unlike the fakes) never stall a 16 MiB payload read.
_SETTINGS_MAX_FRAME_SIZE = 0x5
_SETTINGS_INITIAL_WINDOW = 0x4
_CLIENT_SETTINGS = struct.pack(
    "!HIHI",
    _SETTINGS_INITIAL_WINDOW, 2**31 - 1,
    _SETTINGS_MAX_FRAME_SIZE, 2**24 - 1,
)
_CONN_WINDOW_TOPUP = struct.pack("!I", 2**30)

_DEFAULT_MAX_FRAME = 16384


def _transient(msg: str) -> StorageError:
    return StorageError(msg, transient=True)


class _WireConn:
    """One HTTP/2 connection carrying one RPC at a time."""

    def __init__(
        self,
        host: str,
        port: int,
        *,
        tls: bool,
        cafile: Optional[str],
        insecure_skip_verify: bool,
        authority: str,
        connect_timeout_s: float,
    ):
        self.authority = authority
        self.scheme = "https" if tls else "http"
        self.broken = False
        self._next_stream = 1
        # Max DATA payload the SERVER allows us to send (its SETTINGS).
        self._peer_max_frame = _DEFAULT_MAX_FRAME
        self._wlock = threading.Lock()
        sock = socket.create_connection((host, port), timeout=connect_timeout_s)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        if tls:
            ctx = ssl.create_default_context(cafile=cafile or None)
            ctx.set_alpn_protocols(["h2"])
            if insecure_skip_verify:
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
            sock = ctx.wrap_socket(sock, server_hostname=host)
        self.sock = sock
        with self._wlock:
            self.sock.sendall(_PREFACE)
        self.send_frame(_SETTINGS, 0, 0, _CLIENT_SETTINGS)
        self.send_frame(_WINDOW_UPDATE, 0, 0, _CONN_WINDOW_TOPUP)

    # ---------------------------------------------------------- frame io --
    def send_frame(self, ftype: int, flags: int, stream: int, payload: bytes):
        hdr = struct.pack("!I", len(payload))[1:] + bytes(
            [ftype, flags]
        ) + struct.pack("!I", stream & 0x7FFFFFFF)
        with self._wlock:
            self.sock.sendall(hdr + payload)

    def recv_frame(
        self, deadline_ns: int
    ) -> Optional[tuple[int, int, int, bytes]]:
        """(type, flags, stream, payload) or None at clean EOF."""
        hdr = self._recv_all(9, deadline_ns)
        if hdr is None:
            return None
        flen = (hdr[0] << 16) | (hdr[1] << 8) | hdr[2]
        ftype, fflags = hdr[3], hdr[4]
        stream = struct.unpack("!I", hdr[5:9])[0] & 0x7FFFFFFF
        payload = b""
        if flen:
            payload = self._recv_all(flen, deadline_ns)
            if payload is None:
                return None
        return ftype, fflags, stream, payload

    def _recv_all(self, n: int, deadline_ns: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            remaining = (deadline_ns - time.perf_counter_ns()) / 1e9
            if remaining <= 0:
                raise socket.timeout("grpc wire deadline")
            self.sock.settimeout(min(remaining, 60.0))
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                # EOF — mid-frame or between frames, the RPC is dead
                # either way; callers classify as transient.
                return None
            buf += chunk
        return buf

    def note_peer_settings(self, payload: bytes) -> None:
        for off in range(0, len(payload) - 5, 6):
            ident, value = struct.unpack_from("!HI", payload, off)
            if ident == _SETTINGS_MAX_FRAME_SIZE:
                self._peer_max_frame = value

    def next_stream_id(self) -> int:
        sid = self._next_stream
        self._next_stream += 2
        return sid

    def close(self) -> None:
        self.broken = True
        try:
            self.sock.close()
        except OSError:
            pass


class WireCall:
    """One in-flight RPC on a leased connection.

    Send side: :meth:`send_message` (``end=True`` half-closes). Receive
    side: :meth:`recv_message` returns the next response message, or
    ``None`` once OK trailers arrived; non-OK trailers raise the
    classified StorageError. :meth:`close` returns the connection to
    the channel (reusable only after a clean end)."""

    def __init__(self, channel: "GrpcWireChannel", conn: _WireConn, method: str):
        self._channel = channel
        self._conn = conn
        self.method = method
        self.stream_id = conn.next_stream_id()
        self._decoder = FrameDecoder()
        self._deadline_ns = time.perf_counter_ns() + int(
            channel.timeout_s * 1e9
        )
        self._trailers_status: Optional[int] = None
        self._trailers_message = ""
        self._remote_closed = False
        self._finished = False
        block = b"".join(
            _hp_literal(k, v)
            for k, v in (
                (":method", "POST"),
                (":scheme", conn.scheme),
                (":path", method),
                (":authority", conn.authority),
                ("te", "trailers"),
                ("content-type", "application/grpc"),
            )
        )
        conn.send_frame(
            _HEADERS, _FLAG_END_HEADERS, self.stream_id, block
        )
        annotate("grpc_frame", dir="open", method=method)

    # -------------------------------------------------------------- send --
    def send_message(self, msg: bytes, end: bool = False) -> None:
        """Frame + send one gRPC message, chunked to the server's
        advertised max frame size; ``end=True`` half-closes our side."""
        framed = encode_frame(msg)
        try:
            mv = memoryview(framed)
            step = self._conn._peer_max_frame
            for off in range(0, len(mv), step):
                chunk = mv[off : off + step]
                last = off + step >= len(mv)
                self._conn.send_frame(
                    _DATA,
                    _FLAG_END_STREAM if (end and last) else 0,
                    self.stream_id,
                    bytes(chunk),
                )
        except OSError as e:
            self._conn.broken = True
            raise _transient(f"{self.method}: send failed: {e}") from e
        annotate("grpc_frame", dir="send", bytes=len(msg))

    def half_close(self) -> None:
        """END_STREAM with an empty DATA frame (no trailing message)."""
        try:
            self._conn.send_frame(
                _DATA, _FLAG_END_STREAM, self.stream_id, b""
            )
        except OSError as e:
            self._conn.broken = True
            raise _transient(f"{self.method}: half-close failed: {e}") from e

    # -------------------------------------------------------------- recv --
    def recv_message(self) -> Optional[bytes]:
        while True:
            msg = self._decoder.next()
            if msg is not None:
                annotate("grpc_frame", dir="recv", bytes=len(msg))
                return msg
            if self._trailers_status is not None:
                if self._trailers_status != OK:
                    raise status_to_storage_error(
                        self._trailers_status,
                        self._trailers_message,
                        self.method,
                    )
                self._decoder.finish()
                return None
            if self._remote_closed:
                # END_STREAM without grpc-status trailers: the server
                # (or a middlebox) dropped the stream shape.
                self._conn.broken = True
                raise _transient(
                    f"{self.method}: stream ended without grpc-status"
                )
            self._pump()

    def _pump(self) -> None:
        conn = self._conn
        try:
            frame = conn.recv_frame(self._deadline_ns)
        except socket.timeout as e:
            conn.broken = True
            raise StorageError(
                f"{self.method}: grpc wire deadline exceeded "
                f"({self._channel.timeout_s}s)",
                transient=True,
            ) from e
        except OSError as e:
            conn.broken = True
            raise _transient(f"{self.method}: recv failed: {e}") from e
        if frame is None:
            conn.broken = True
            raise _transient(f"{self.method}: connection closed mid-rpc")
        ftype, flags, stream, payload = frame
        if ftype == _SETTINGS:
            if not flags & _FLAG_ACK:
                conn.note_peer_settings(payload)
                conn.send_frame(_SETTINGS, _FLAG_ACK, 0, b"")
            return
        if ftype == _PING:
            if not flags & _FLAG_ACK:
                conn.send_frame(_PING, _FLAG_ACK, 0, payload)
            return
        if ftype == _WINDOW_UPDATE:
            return
        if ftype == _GOAWAY:
            conn.broken = True
            raise _transient(f"{self.method}: server sent GOAWAY")
        if stream != self.stream_id:
            return  # stray frame for a dead stream; ignore
        if ftype == _RST_STREAM:
            conn.broken = True
            code = struct.unpack("!I", payload)[0] if len(payload) >= 4 else 0
            raise _transient(
                f"{self.method}: stream reset by server (h2 error {code})"
            )
        if ftype == _DATA:
            if flags & _FLAG_PADDED and payload:
                pad = payload[0]
                payload = payload[1 : len(payload) - pad]
            self._decoder.feed(payload)
            if flags & _FLAG_END_STREAM:
                self._remote_closed = True
            return
        if ftype == _HEADERS:
            if not flags & _FLAG_END_HEADERS:
                conn.broken = True
                raise _transient(
                    f"{self.method}: fragmented header block (CONTINUATION "
                    "unsupported)"
                )
            if flags & _FLAG_PADDED and payload:
                pad = payload[0]
                payload = payload[1 : len(payload) - pad]
            elif flags & _FLAG_PRIORITY:
                payload = payload[5:]
            try:
                hdrs = decode_request_headers(payload)
            except _HpackError as e:
                conn.broken = True
                raise _transient(f"{self.method}: bad header block: {e}") from e
            if "grpc-status" in hdrs:
                try:
                    self._trailers_status = int(hdrs["grpc-status"])
                except ValueError:
                    self._trailers_status = 2  # UNKNOWN
                self._trailers_message = hdrs.get("grpc-message", "")
                if flags & _FLAG_END_STREAM:
                    self._remote_closed = True
            # else: initial response headers (:status 200) — nothing to do.
            return
        # Unknown frame type: ignore (extension frames are legal).

    # ------------------------------------------------------------- close --
    def cancel(self) -> None:
        """RST_STREAM CANCEL; the connection is discarded (frames from
        the cancelled stream may still be in flight on it)."""
        if self._finished:
            return
        self._finished = True
        try:
            self._conn.send_frame(
                _RST_STREAM, 0, self.stream_id, struct.pack("!I", 0x8)
            )
        except OSError:
            pass
        self._conn.broken = True
        self._channel._release(self._conn)

    def close(self) -> None:
        """Return the connection: reusable iff the RPC ended cleanly."""
        if self._finished:
            return
        self._finished = True
        if not (
            self._remote_closed and self._trailers_status is not None
        ):
            self._conn.broken = True
        self._channel._release(self._conn)


class GrpcWireChannel:
    """Pool of :class:`_WireConn` serving one RPC each, round-robin by
    lease order. ``pool``-sized free-list; concurrency beyond it dials
    ephemeral sockets (dropped on release once the list is full)."""

    def __init__(
        self,
        host: str,
        port: int,
        *,
        tls: bool = False,
        cafile: Optional[str] = None,
        insecure_skip_verify: bool = False,
        authority: Optional[str] = None,
        timeout_s: float = 30.0,
        idle_cap: int = 4,
    ):
        self.host, self.port, self.tls = host, port, tls
        self.cafile = cafile
        self.insecure_skip_verify = insecure_skip_verify
        self.authority = authority or f"{host}:{port}"
        self.timeout_s = timeout_s
        self._idle_cap = idle_cap
        self._idle: list[_WireConn] = []
        self._lock = threading.Lock()
        self.stats = {"connects": 0, "reuses": 0}
        self._closed = False

    # ------------------------------------------------------------- conns --
    def _dial(self) -> _WireConn:
        with self._lock:
            self.stats["connects"] += 1
        try:
            return _WireConn(
                self.host,
                self.port,
                tls=self.tls,
                cafile=self.cafile,
                insecure_skip_verify=self.insecure_skip_verify,
                authority=self.authority,
                connect_timeout_s=min(self.timeout_s, 20.0),
            )
        except OSError as e:
            raise _transient(
                f"grpc wire: connect {self.host}:{self.port} failed: {e}"
            ) from e

    def _lease(self) -> _WireConn:
        with self._lock:
            if self._idle:
                self.stats["reuses"] += 1
                return self._idle.pop()
        return self._dial()

    def _release(self, conn: _WireConn) -> None:
        if conn.broken or self._closed:
            conn.close()
            return
        with self._lock:
            if len(self._idle) < self._idle_cap:
                self._idle.append(conn)
                return
        conn.close()

    # -------------------------------------------------------------- RPCs --
    def start_call(self, method: str) -> WireCall:
        """Open an RPC; caller drives send/recv and must close()."""
        conn = self._lease()
        try:
            return WireCall(self, conn, method)
        except OSError:
            # Stale keep-alive socket: one fresh dial, then give up to
            # the retry plane above.
            conn.close()
            conn = self._dial()
            try:
                return WireCall(self, conn, method)
            except OSError as e:
                conn.close()
                raise _transient(f"{method}: send failed: {e}") from e

    def unary(self, method: str, request: bytes) -> bytes:
        """One request in, exactly one response message out."""
        call = self.start_call(method)
        try:
            call.send_message(request, end=True)
            resp = call.recv_message()
            if resp is None:
                raise _transient(f"{method}: OK trailers with no response")
            # Drain to trailers so the conn is clean for reuse.
            while call.recv_message() is not None:
                pass
            return resp
        except BaseException:
            call.cancel()
            raise
        finally:
            call.close()

    def server_stream(self, method: str, request: bytes) -> WireCall:
        """Send the one request, return the call for streamed reads."""
        call = self.start_call(method)
        try:
            call.send_message(request, end=True)
        except BaseException:
            call.cancel()
            raise
        return call

    def bidi(self, method: str) -> WireCall:
        """Open a bidi stream; caller interleaves send/recv."""
        return self.start_call(method)

    def close(self) -> None:
        with self._lock:
            self._closed = True
            idle, self._idle = self._idle, []
        for conn in idle:
            conn.close()
