"""gRPC message framing and status mapping (no grpcio).

Every gRPC message rides HTTP/2 DATA as a 5-byte-prefixed frame:
1 byte compressed-flag (tpubench never compresses) + 4-byte big-endian
message length + the protobuf payload. The RPC outcome travels in
HTTP/2 trailers as ``grpc-status`` / ``grpc-message``.

:class:`FrameDecoder` is an incremental parser shared by the wire
client and the fake wire server: feed it DATA payloads as they arrive,
pull complete messages out. Malformed input — a set compressed flag,
an oversized length, bytes left dangling at stream end — is always a
classified :class:`WireCodecError`, never a hang or a silent short
read (satellite 6's contract).

Status mapping mirrors ``gcs_grpc``'s library-mode tables: transient
codes retry under ``_ResumingWriter``/``RetryingBackend``; the
HTTP-ish codes keep fault-plan assertions (404/412/416/503) uniform
across h1, h2 and gRPC transports.
"""

from __future__ import annotations

import struct
from typing import Optional, Union

from tpubench.storage.base import StorageError

# gRPC status codes (the subset tpubench maps; numbering is canonical).
OK = 0
UNKNOWN = 2
INVALID_ARGUMENT = 3
DEADLINE_EXCEEDED = 4
NOT_FOUND = 5
FAILED_PRECONDITION = 9
ABORTED = 10
OUT_OF_RANGE = 11
INTERNAL = 13
UNAVAILABLE = 14

_STATUS_NAMES = {
    OK: "OK",
    UNKNOWN: "UNKNOWN",
    INVALID_ARGUMENT: "INVALID_ARGUMENT",
    DEADLINE_EXCEEDED: "DEADLINE_EXCEEDED",
    NOT_FOUND: "NOT_FOUND",
    FAILED_PRECONDITION: "FAILED_PRECONDITION",
    ABORTED: "ABORTED",
    OUT_OF_RANGE: "OUT_OF_RANGE",
    INTERNAL: "INTERNAL",
    UNAVAILABLE: "UNAVAILABLE",
}

# Same transient set as gcs_grpc._TRANSIENT_STATUS_INTS (library mode):
# the retry planes must classify identically whichever stack decoded
# the status.
TRANSIENT_STATUS = frozenset(
    {DEADLINE_EXCEEDED, ABORTED, INTERNAL, UNAVAILABLE, 8}  # 8 = RESOURCE_EXHAUSTED
)

# gRPC status → the HTTP-ish StorageError.code the rest of tpubench
# asserts on (fault plans, lifecycle preconditions, range sentinels).
STATUS_TO_HTTPISH = {
    INVALID_ARGUMENT: 400,
    NOT_FOUND: 404,
    FAILED_PRECONDITION: 412,
    OUT_OF_RANGE: 416,
    UNAVAILABLE: 503,
}
HTTPISH_TO_STATUS = {v: k for k, v in STATUS_TO_HTTPISH.items()}

# Ceiling on a single decoded message. Server chunks reads at 2 MiB
# (MAX_READ_CHUNK); metadata responses are tiny. 4x headroom guards
# against a corrupt length prefix allocating gigabytes.
MAX_MESSAGE_BYTES = 8 * 1024 * 1024


class WireCodecError(StorageError):
    """Malformed wire bytes (framing or protobuf). Never transient:
    retrying a corrupt stream replays the corruption."""

    def __init__(self, msg: str):
        super().__init__(f"grpc wire: {msg}", transient=False, code=400)


def encode_frame(msg: Union[bytes, bytearray, memoryview]) -> bytes:
    """5-byte-prefix a serialized protobuf message (uncompressed)."""
    return b"\x00" + struct.pack("!I", len(msg)) + bytes(msg)


class FrameDecoder:
    """Incremental gRPC frame parser.

    ``feed()`` DATA-frame payloads as they arrive; ``next()`` returns
    one complete message (``bytes``) or ``None`` when more input is
    needed; ``finish()`` asserts no partial frame is left dangling at
    end-of-stream.
    """

    def __init__(self, max_message: int = MAX_MESSAGE_BYTES):
        self._buf = bytearray()
        self._max = max_message

    def feed(self, data: Union[bytes, bytearray, memoryview]) -> None:
        self._buf += data

    def next(self) -> Optional[bytes]:
        buf = self._buf
        if len(buf) < 5:
            return None
        if buf[0] != 0:
            raise WireCodecError(
                f"compressed flag {buf[0]:#x} (compression unsupported)"
            )
        (ln,) = struct.unpack_from("!I", buf, 1)
        if ln > self._max:
            raise WireCodecError(
                f"message length {ln} exceeds cap {self._max}"
            )
        if len(buf) < 5 + ln:
            return None
        msg = bytes(buf[5 : 5 + ln])
        del buf[: 5 + ln]
        return msg

    def pending(self) -> int:
        """Bytes buffered but not yet yielded (0 iff frame-aligned)."""
        return len(self._buf)

    def finish(self) -> None:
        if self._buf:
            raise WireCodecError(
                f"stream ended mid-frame ({len(self._buf)} bytes of "
                "partial gRPC frame)"
            )


def status_to_storage_error(
    status: int, message: str, what: str
) -> StorageError:
    """Map a non-OK grpc-status trailer to a classified StorageError."""
    name = _STATUS_NAMES.get(status, str(status))
    return StorageError(
        f"{what}: grpc status {name}: {message or '(no message)'}",
        transient=status in TRANSIENT_STATUS,
        code=STATUS_TO_HTTPISH.get(status),
    )


def storage_error_to_status(e: StorageError) -> tuple[int, str]:
    """Reverse map for the fake wire server's trailers.

    Injected connection resets (code 104) never reach here — the
    server kills the socket abruptly instead, so the client exercises
    its EOF/RST path exactly as against a real mid-stream drop.
    """
    code = getattr(e, "code", None)
    if code in HTTPISH_TO_STATUS:
        return HTTPISH_TO_STATUS[code], str(e)
    if getattr(e, "transient", False):
        return UNAVAILABLE, str(e)
    return UNKNOWN, str(e)
