"""Hand-rolled protobuf wire codec for the storage-v2 messages.

Proto3 wire format, the slice tpubench needs (no dependency on
``protobuf``): varints, length-delimited fields, fixed32 — declared per
message as ``FIELDS = {number: (attr, kind)}`` and driven by one
generic encoder/decoder. Field numbers are pinned to
``google/storage/v2/storage.proto`` (the same constants the native
engine's hand-rolled client uses, engine.cc — the in-repo interop
anchor), so wire-mode Python, the C++ engine and the real service all
speak one schema.

Decoding skips unknown fields by wire type (a real server may send
fields this codec doesn't model); every truncation is a classified
:class:`WireCodecError`, never a silent short read.

Kinds: ``str`` / ``bytes`` / ``varint`` (proto3 implicit presence:
zero/empty values are not encoded) / ``bool`` / ``ovarint`` (explicit
presence — ``None`` = absent, 0 is encoded: ``if_generation_match=0``
means "object must not exist") / ``fixed32`` (``None`` = absent, for
crc32c) / ``("msg", cls)`` / ``("rep", cls)``.
"""

from __future__ import annotations

from typing import Optional, Union

from tpubench.storage.grpc_wire.framing import WireCodecError

# Varints are unbounded on the wire; 64 bits is the proto ceiling and
# anything longer is a malformed (or hostile) stream.
_MAX_VARINT_BYTES = 10


def encode_varint(v: int) -> bytes:
    if v < 0:
        raise WireCodecError(f"varint must be non-negative, got {v}")
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def decode_varint(data, i: int) -> tuple[int, int]:
    """(value, next_index); raises on truncation or overlong varints."""
    v = 0
    shift = 0
    n = len(data)
    for _ in range(_MAX_VARINT_BYTES):
        if i >= n:
            raise WireCodecError("truncated varint")
        b = data[i]
        i += 1
        v |= (b & 0x7F) << shift
        if not b & 0x80:
            return v, i
        shift += 7
    raise WireCodecError("varint longer than 10 bytes")


def _tag(field: int, wtype: int) -> bytes:
    return encode_varint((field << 3) | wtype)


def _enc_len_delim(field: int, payload: bytes) -> bytes:
    return _tag(field, 2) + encode_varint(len(payload)) + payload


def _skip_field(data, i: int, wtype: int) -> int:
    if wtype == 0:
        _, i = decode_varint(data, i)
        return i
    if wtype == 1:
        i += 8
    elif wtype == 2:
        ln, i = decode_varint(data, i)
        i += ln
    elif wtype == 5:
        i += 4
    else:
        raise WireCodecError(f"unsupported wire type {wtype}")
    if i > len(data):
        raise WireCodecError("field payload past end of message")
    return i


def _default(kind) -> object:
    if isinstance(kind, tuple):
        return [] if kind[0] == "rep" else None
    return {
        "str": "", "bytes": b"", "varint": 0, "bool": False,
        "ovarint": None, "fixed32": None,
    }[kind]


class Msg:
    """Base for declarative messages: ``FIELDS = {num: (attr, kind)}``."""

    FIELDS: dict[int, tuple[str, Union[str, tuple]]] = {}

    def __init__(self, **kw):
        for _num, (attr, kind) in self.FIELDS.items():
            setattr(self, attr, kw.pop(attr, _default(kind)))
        if kw:
            raise TypeError(
                f"{type(self).__name__}: unknown fields {sorted(kw)}"
            )

    def __repr__(self) -> str:  # debugging/test failure readability
        pairs = ", ".join(
            f"{attr}={getattr(self, attr)!r}"
            for _n, (attr, _k) in sorted(self.FIELDS.items())
            if getattr(self, attr) not in (None, "", b"", 0, False, [])
        )
        return f"{type(self).__name__}({pairs})"

    # ------------------------------------------------------------ encode --
    def encode(self) -> bytes:
        out = bytearray()
        for num, (attr, kind) in sorted(self.FIELDS.items()):
            v = getattr(self, attr)
            if isinstance(kind, tuple):
                tag, cls = kind
                if tag == "msg":
                    if v is not None:
                        out += _enc_len_delim(num, v.encode())
                else:  # rep
                    for item in v:
                        out += _enc_len_delim(num, item.encode())
            elif kind == "str":
                if v:
                    out += _enc_len_delim(num, v.encode("utf-8"))
            elif kind == "bytes":
                if v:
                    out += _enc_len_delim(num, bytes(v))
            elif kind == "varint":
                if v:
                    out += _tag(num, 0) + encode_varint(int(v))
            elif kind == "bool":
                if v:
                    out += _tag(num, 0) + encode_varint(1)
            elif kind == "ovarint":
                if v is not None:
                    out += _tag(num, 0) + encode_varint(int(v))
            elif kind == "fixed32":
                if v is not None:
                    out += _tag(num, 5) + int(v).to_bytes(4, "little")
            else:  # pragma: no cover - schema bug
                raise WireCodecError(f"unknown field kind {kind!r}")
        return bytes(out)

    # ------------------------------------------------------------ decode --
    @classmethod
    def decode(cls, data) -> "Msg":
        if isinstance(data, memoryview):
            data = bytes(data)
        self = cls()
        i, n = 0, len(data)
        while i < n:
            key, i = decode_varint(data, i)
            num, wtype = key >> 3, key & 0x7
            spec = cls.FIELDS.get(num)
            if spec is None:
                i = _skip_field(data, i, wtype)
                continue
            attr, kind = spec
            if isinstance(kind, tuple) or kind in ("str", "bytes"):
                if wtype != 2:
                    raise WireCodecError(
                        f"{cls.__name__}.{attr}: wire type {wtype}, "
                        "expected length-delimited"
                    )
                ln, i = decode_varint(data, i)
                if i + ln > n:
                    raise WireCodecError(
                        f"{cls.__name__}.{attr}: length {ln} past end"
                    )
                payload = data[i : i + ln]
                i += ln
                if isinstance(kind, tuple):
                    tag, sub = kind
                    if tag == "msg":
                        setattr(self, attr, sub.decode(payload))
                    else:
                        getattr(self, attr).append(sub.decode(payload))
                elif kind == "str":
                    setattr(self, attr, payload.decode("utf-8"))
                else:
                    setattr(self, attr, bytes(payload))
            elif kind in ("varint", "ovarint", "bool"):
                if wtype != 0:
                    raise WireCodecError(
                        f"{cls.__name__}.{attr}: wire type {wtype}, "
                        "expected varint"
                    )
                v, i = decode_varint(data, i)
                setattr(self, attr, bool(v) if kind == "bool" else v)
            elif kind == "fixed32":
                if wtype != 5:
                    raise WireCodecError(
                        f"{cls.__name__}.{attr}: wire type {wtype}, "
                        "expected fixed32"
                    )
                if i + 4 > n:
                    raise WireCodecError(f"{cls.__name__}.{attr}: truncated fixed32")
                setattr(self, attr, int.from_bytes(data[i : i + 4], "little"))
                i += 4
        return self


# ------------------------------------------------- storage-v2 messages ----
# Field numbers from google/storage/v2/storage.proto (subset).


class Object(Msg):
    FIELDS = {
        1: ("name", "str"),
        2: ("bucket", "str"),
        3: ("generation", "varint"),
        6: ("size", "varint"),
    }


class ChecksummedData(Msg):
    FIELDS = {
        1: ("content", "bytes"),
        2: ("crc32c", "fixed32"),
    }


class ObjectChecksums(Msg):
    FIELDS = {
        1: ("crc32c", "fixed32"),
    }


class ReadObjectRequest(Msg):
    FIELDS = {
        1: ("bucket", "str"),
        2: ("object", "str"),
        3: ("generation", "varint"),
        4: ("read_offset", "varint"),
        5: ("read_limit", "varint"),
    }


class ReadObjectResponse(Msg):
    FIELDS = {
        1: ("checksummed_data", ("msg", ChecksummedData)),
        4: ("metadata", ("msg", Object)),
    }


class GetObjectRequest(Msg):
    FIELDS = {
        1: ("bucket", "str"),
        2: ("object", "str"),
        3: ("generation", "varint"),
    }


class ListObjectsRequest(Msg):
    FIELDS = {
        1: ("parent", "str"),
        2: ("page_size", "varint"),
        3: ("page_token", "str"),
        6: ("prefix", "str"),
    }


class ListObjectsResponse(Msg):
    FIELDS = {
        1: ("objects", ("rep", Object)),
        3: ("next_page_token", "str"),
    }


class DeleteObjectRequest(Msg):
    FIELDS = {
        1: ("bucket", "str"),
        2: ("object", "str"),
    }


class WriteObjectSpec(Msg):
    # if_generation_match has EXPLICIT presence in the real proto
    # (optional int64): 0 means "must not exist" and must hit the wire.
    FIELDS = {
        1: ("resource", ("msg", Object)),
        3: ("if_generation_match", "ovarint"),
    }


class WriteObjectRequest(Msg):
    FIELDS = {
        1: ("upload_id", "str"),
        2: ("write_object_spec", ("msg", WriteObjectSpec)),
        3: ("write_offset", "varint"),
        4: ("checksummed_data", ("msg", ChecksummedData)),
        6: ("object_checksums", ("msg", ObjectChecksums)),
        7: ("finish_write", "bool"),
    }


class WriteObjectResponse(Msg):
    FIELDS = {
        1: ("persisted_size", "varint"),
        2: ("resource", ("msg", Object)),
    }


class StartResumableWriteRequest(Msg):
    FIELDS = {
        1: ("write_object_spec", ("msg", WriteObjectSpec)),
    }


class StartResumableWriteResponse(Msg):
    FIELDS = {
        1: ("upload_id", "str"),
    }


class QueryWriteStatusRequest(Msg):
    FIELDS = {
        1: ("upload_id", "str"),
    }


class QueryWriteStatusResponse(Msg):
    FIELDS = {
        1: ("persisted_size", "varint"),
        2: ("resource", ("msg", Object)),
    }


class BidiWriteObjectRequest(Msg):
    FIELDS = {
        1: ("upload_id", "str"),
        2: ("write_object_spec", ("msg", WriteObjectSpec)),
        3: ("write_offset", "varint"),
        4: ("checksummed_data", ("msg", ChecksummedData)),
        6: ("object_checksums", ("msg", ObjectChecksums)),
        7: ("state_lookup", "bool"),
        8: ("flush", "bool"),
        9: ("finish_write", "bool"),
    }


class BidiWriteObjectResponse(Msg):
    FIELDS = {
        1: ("persisted_size", "varint"),
        2: ("resource", ("msg", Object)),
    }


def crc32c_of(data) -> Optional[int]:
    """CRC32C when the accelerated library rides along with the image,
    else ``None`` (the checksummed fields stay absent — a pure-Python
    CRC in the hot loop would turn a transport benchmark into a
    checksum benchmark)."""
    try:
        import google_crc32c
    except ImportError:
        return None
    return int(google_crc32c.value(bytes(data)))
