"""Local/FUSE filesystem backend (reference ``benchmark-script/`` L0 path).

The reference's five FS drivers exercise a gcsfuse mount or local SSD
through ``os.OpenFile`` + O_DIRECT. Here:

* this backend implements the generic :class:`StorageBackend` protocol over
  a directory root (objects = relative file paths) via ``pread`` — usable
  anywhere the protocol is (read workload, pod ingest, staging);
* the O_DIRECT *block-level* benchmarks (read_fs / write / ssd_compare
  workloads) use :mod:`tpubench.native` directly, because O_DIRECT needs
  aligned buffers the protocol's caller-owned granules can't guarantee
  (SURVEY hard-part (e)).
"""

from __future__ import annotations

import os
from typing import Optional

from tpubench.storage.base import ObjectMeta, StorageError


class _FileReader:
    def __init__(self, fd: int, start: int, length: int):
        self._fd = fd
        self._pos = start
        self._end = start + length
        self.first_byte_ns: Optional[int] = None

    def readinto(self, buf: memoryview) -> int:
        import time

        want = min(len(buf), self._end - self._pos)
        if want <= 0:
            return 0
        try:
            data = os.pread(self._fd, want, self._pos)
        except OSError as e:
            raise StorageError(f"pread failed: {e}", transient=False) from e
        n = len(data)
        if n == 0:
            return 0
        buf[:n] = data
        if self.first_byte_ns is None:
            self.first_byte_ns = time.perf_counter_ns()
        self._pos += n
        return n

    def close(self) -> None:
        os.close(self._fd)
        self._fd = -1


class LocalFsBackend:
    def __init__(self, root: str):
        if not root:
            raise ValueError("local backend needs workload.dir")
        self.root = root

    def _path(self, name: str) -> str:
        p = os.path.normpath(os.path.join(self.root, name))
        if not p.startswith(os.path.normpath(self.root)):
            raise StorageError(f"path escapes root: {name}", transient=False)
        return p

    def open_read(self, name: str, start: int = 0, length: Optional[int] = None):
        path = self._path(name)
        try:
            fd = os.open(path, os.O_RDONLY)
        except FileNotFoundError:
            raise StorageError(f"object not found: {name}", transient=False, code=404)
        except OSError as e:
            raise StorageError(f"open failed: {e}", transient=False) from e
        size = os.fstat(fd).st_size
        end = size if length is None else min(start + length, size)
        return _FileReader(fd, start, max(0, end - start))

    def write(self, name: str, data: bytes) -> ObjectMeta:
        path = self._path(name)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        return ObjectMeta(name, len(data))

    def list(self, prefix: str = "") -> list[ObjectMeta]:
        out = []
        for dirpath, _, files in os.walk(self.root):
            for fname in files:
                full = os.path.join(dirpath, fname)
                rel = os.path.relpath(full, self.root)
                if rel.startswith(prefix):
                    out.append(ObjectMeta(rel, os.path.getsize(full)))
        return sorted(out, key=lambda m: m.name)

    def stat(self, name: str) -> ObjectMeta:
        path = self._path(name)
        try:
            return ObjectMeta(name, os.path.getsize(path))
        except FileNotFoundError:
            raise StorageError(f"object not found: {name}", transient=False, code=404)

    def delete(self, name: str) -> None:
        try:
            os.remove(self._path(name))
        except FileNotFoundError:
            raise StorageError(f"object not found: {name}", transient=False, code=404)

    def close(self) -> None:
        pass
