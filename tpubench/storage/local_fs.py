"""Local/FUSE filesystem backend (reference ``benchmark-script/`` L0 path).

The reference's five FS drivers exercise a gcsfuse mount or local SSD
through ``os.OpenFile`` + O_DIRECT. Here:

* this backend implements the generic :class:`StorageBackend` protocol over
  a directory root (objects = relative file paths) via ``pread`` — usable
  anywhere the protocol is (read workload, pod ingest, staging);
* the O_DIRECT *block-level* benchmarks (read_fs / write / ssd_compare
  workloads) use :mod:`tpubench.native` directly, because O_DIRECT needs
  aligned buffers the protocol's caller-owned granules can't guarantee
  (SURVEY hard-part (e)).
"""

from __future__ import annotations

import os
from typing import Optional

from tpubench.storage.base import ObjectMeta, StorageError


class _FileReader:
    def __init__(self, fd: int, start: int, length: int):
        self._fd = fd
        self._pos = start
        self._end = start + length
        self.first_byte_ns: Optional[int] = None

    def readinto(self, buf: memoryview) -> int:
        import time

        want = min(len(buf), self._end - self._pos)
        if want <= 0:
            return 0
        try:
            data = os.pread(self._fd, want, self._pos)
        except OSError as e:
            raise StorageError(f"pread failed: {e}", transient=False) from e
        n = len(data)
        if n == 0:
            return 0
        buf[:n] = data
        if self.first_byte_ns is None:
            self.first_byte_ns = time.perf_counter_ns()
        self._pos += n
        return n

    def close(self) -> None:
        os.close(self._fd)
        self._fd = -1


class _FsWriter:
    """ObjectWriter over a hidden ``.part`` staging file (see
    LocalFsBackend.open_write). ``offset`` tracks the fsynced size —
    the durable committed watermark a crashed-and-resumed session can
    re-probe with ``committed()``."""

    def __init__(self, backend: "LocalFsBackend", name: str,
                 if_generation_match):
        self._backend = backend
        self.name = name
        self._igm = if_generation_match
        self._final_path = backend._path(name)
        self._part_path = self._final_path + ".part"
        os.makedirs(os.path.dirname(self._part_path), exist_ok=True)
        # Resume an interrupted session when a part file already exists
        # (the FS twin of re-probing a live session URL).
        self.offset = (
            os.path.getsize(self._part_path)
            if os.path.exists(self._part_path) else 0
        )
        self._done = False

    def write(self, data) -> int:
        if self._done:
            raise StorageError(
                f"writer for {self.name!r} already finalized",
                transient=False, code=400,
            )
        payload = bytes(data)
        try:
            fd = os.open(self._part_path, os.O_WRONLY | os.O_CREAT)
            try:
                os.lseek(fd, self.offset, os.SEEK_SET)
                written = 0
                while written < len(payload):
                    # os.write may write SHORT (near-full fs, signals);
                    # an unchecked return would advance the watermark
                    # past bytes that never landed.
                    n = os.write(fd, payload[written:])
                    if n <= 0:
                        raise OSError("zero-byte write")
                    written += n
                os.fsync(fd)
            finally:
                os.close(fd)
        except OSError as e:
            raise StorageError(f"part write failed: {e}", transient=False) from e
        self.offset += len(payload)
        return self.offset

    def committed(self) -> int:
        self.offset = (
            os.path.getsize(self._part_path)
            if os.path.exists(self._part_path) else self.offset
        )
        return self.offset

    def finalize(self) -> ObjectMeta:
        if self._done:
            return ObjectMeta(self.name, self.offset, 1)
        self._backend._check_generation(self.name, self._igm)
        try:
            os.replace(self._part_path, self._final_path)
        except OSError as e:
            raise StorageError(f"finalize failed: {e}", transient=False) from e
        self._done = True
        return ObjectMeta(self.name, self.offset, 1)

    def abort(self) -> None:
        try:
            os.remove(self._part_path)
        except OSError:
            pass


class LocalFsBackend:
    def __init__(self, root: str):
        if not root:
            raise ValueError("local backend needs workload.dir")
        self.root = root

    def _path(self, name: str) -> str:
        p = os.path.normpath(os.path.join(self.root, name))
        if not p.startswith(os.path.normpath(self.root)):
            raise StorageError(f"path escapes root: {name}", transient=False)
        return p

    def open_read(self, name: str, start: int = 0, length: Optional[int] = None):
        path = self._path(name)
        try:
            fd = os.open(path, os.O_RDONLY)
        except FileNotFoundError:
            raise StorageError(f"object not found: {name}", transient=False, code=404)
        except OSError as e:
            raise StorageError(f"open failed: {e}", transient=False) from e
        size = os.fstat(fd).st_size
        end = size if length is None else min(start + length, size)
        return _FileReader(fd, start, max(0, end - start))

    def _check_generation(self, name: str, want) -> None:
        """FS generation model (the one a filesystem can honestly offer):
        an existing file is generation 1, an absent one 0 — so
        ``if_generation_match=0`` is the create-only precondition and 1
        the overwrite-only one. Mismatch is the same non-transient 412
        the object stores raise."""
        if want is None:
            return
        current = 1 if os.path.exists(self._path(name)) else 0
        if current != want:
            raise StorageError(
                f"if_generation_match={want} does not match FS state "
                f"{current} of {name!r}", transient=False, code=412,
            )

    def write(self, name: str, data: bytes,
              if_generation_match=None) -> ObjectMeta:
        self._check_generation(name, if_generation_match)
        path = self._path(name)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        return ObjectMeta(name, len(data), 1)

    def open_write(self, name: str, if_generation_match=None):
        """Resumable session, FS edition: parts append to a hidden
        ``.part`` sibling (committed offset = its size, durable via
        fsync per part — the write_operations fsync discipline), finalize
        fsyncs and atomically renames into place. The precondition is
        checked at finalize, commit-time like the object stores."""
        return _FsWriter(self, name, if_generation_match)

    def list(self, prefix: str = "", page_size: int = 0) -> list[ObjectMeta]:
        # page_size is a wire concept; a directory walk has no pages.
        out = []
        for dirpath, _, files in os.walk(self.root):
            for fname in files:
                if fname.endswith(".part"):
                    continue  # in-flight resumable sessions are invisible
                full = os.path.join(dirpath, fname)
                rel = os.path.relpath(full, self.root)
                if rel.startswith(prefix):
                    out.append(ObjectMeta(rel, os.path.getsize(full), 1))
        return sorted(out, key=lambda m: m.name)

    def stat(self, name: str) -> ObjectMeta:
        path = self._path(name)
        try:
            return ObjectMeta(name, os.path.getsize(path), 1)
        except FileNotFoundError:
            raise StorageError(f"object not found: {name}", transient=False, code=404)

    def delete(self, name: str) -> None:
        try:
            os.remove(self._path(name))
        except FileNotFoundError:
            raise StorageError(f"object not found: {name}", transient=False, code=404)

    def close(self) -> None:
        pass
