"""Shared pool of native connection handles (engine ``tb_conn``).

Both native receive paths — HTTP (:mod:`gcs_http`) and gRPC/h2
(:mod:`gcs_grpc`) — pool engine connection handles with identical
discipline:

* bounded idle pool (``max_idle_conns_per_host``, main.go:32 analog);
* ``connects`` / ``reuses`` / ``stale_retries`` accounting under the pool
  lock;
* one immediate retransmit on a fresh connection when the FIRST use of a
  pooled handle fails (a socket that died while idle is a normal pool
  condition, not a request failure — standard HTTP-client behavior).

This module is that discipline, written once. The backends supply the
protocol-specific parts: how to connect, how to run one request, whether a
result leaves the connection reusable, and which errors prove the server
actually answered (those must NOT be retried as staleness).
"""

from __future__ import annotations

import threading
from typing import Callable

from tpubench.native.engine import PERMANENT_CODES, NativeError
from tpubench.obs.flight import annotate as flight_annotate
from tpubench.obs.flight import note_phase as flight_note
from tpubench.storage.base import StorageError


def build_native_pool(
    transport, host: str, port: int, tls: bool, alpn_h2: bool = False
) -> "NativeConnPool":
    """The one way both backends construct their native pool: engine
    availability and TLS loadability checks, then a connect closure that
    classifies failures on the engine's code ABI. Callers guard the lazy
    single assignment with their own lock (worker threads hit first use
    concurrently)."""
    from tpubench.native.engine import PERMANENT_CODES, get_engine

    engine = get_engine()
    if engine is None:
        raise StorageError(
            "transport.native_receive=True but the native engine is "
            "unavailable (C++ toolchain missing?)", transient=False
        )
    if tls and not engine.tls_available():
        raise StorageError(
            "transport.native_receive on a TLS endpoint, but the engine "
            "could not load OpenSSL (libssl.so.3)", transient=False
        )

    def connect() -> int:
        try:
            return engine.connect(
                host, port, tls=tls, sni=host,
                cafile=transport.tls_ca_file,
                insecure=transport.tls_insecure_skip_verify,
                alpn_h2=alpn_h2,
            )
        except NativeError as e:
            # Connect/handshake failures classify on the code ABI
            # (handshake/verification = TB_ETLS, permanent).
            raise StorageError(
                f"native connect {host}:{port}: {e}",
                transient=e.code not in PERMANENT_CODES,
            ) from e

    return NativeConnPool(engine, connect, transport.max_idle_conns_per_host)


class BufferPool:
    """Free-list of aligned receive buffers, bucketed by exact size.

    A fresh ``posix_memalign`` per GET means an mmap + page-fault storm on
    every read (allocations past the malloc mmap threshold return untouched
    pages): measured 4-worker throughput DROPPED ~30% below the Python
    client until buffers were reused. Benchmark object sizes repeat, so
    exact-size bucketing hits almost always.
    """

    def __init__(self, engine, max_per_size: int = 8):
        self._engine = engine
        self._lock = threading.Lock()
        self._free: dict[int, list] = {}
        self._max_per_size = max_per_size
        self._closed = False

    def acquire(self, size: int):
        with self._lock:
            bucket = self._free.get(size)
            if bucket:
                return bucket.pop()
        return self._engine.alloc(size)

    def release(self, buf) -> None:
        with self._lock:
            if not self._closed:
                bucket = self._free.setdefault(buf.size, [])
                if len(bucket) < self._max_per_size:
                    bucket.append(buf)
                    return
        # Pool full — or already closed (a straggler reader finishing
        # during shutdown must not repopulate a drained pool): free now.
        buf.free()

    def close(self) -> None:
        with self._lock:
            self._closed = True
            buckets, self._free = list(self._free.values()), {}
        for bucket in buckets:
            for buf in bucket:
                buf.free()


class NativeConnPool:
    """Pool of engine connection handles with one stale-use retry.

    ``connect`` returns a fresh handle; it must raise for itself (the pool
    adds no classification). Its failures propagate unchanged.
    """

    def __init__(self, engine, connect: Callable[[], int], max_idle: int):
        self.engine = engine
        self._connect = connect
        self._idle: list[int] = []
        self._lock = threading.Lock()
        self._max_idle = max_idle
        self.stats = {"connects": 0, "reuses": 0, "stale_retries": 0}
        # The receive BufferPool always accompanies the connection pool
        # (constructed here, drained by close()) — one lifecycle.
        self.buffers = BufferPool(engine)

    # Tests reach into the idle list to inject dead handles.
    @property
    def idle(self) -> list[int]:
        return self._idle

    def _new(self) -> int:
        h = self._connect()
        with self._lock:
            self.stats["connects"] += 1
        flight_note("connect")  # flight-recorder phase (no-op off-op)
        return h

    def fresh(self) -> int:
        """A guaranteed-fresh connection (stale-retry path: a second pooled
        handle could be just as stale as the first)."""
        return self._new()

    def acquire(self) -> tuple[int, bool]:
        """(handle, reused) — a pooled idle handle when available, else a
        fresh connection. The caller owns it until :meth:`release` or
        :meth:`discard` (streaming readers hold it across body reads)."""
        with self._lock:
            conn = self._idle.pop() if self._idle else 0
            if conn:
                self.stats["reuses"] += 1
        if conn:
            return conn, True
        return self._new(), False

    def release(self, conn: int, reusable: bool) -> None:
        """Return a handle: back to the idle pool when ``reusable`` and
        there is room, else closed."""
        if reusable:
            with self._lock:
                if len(self._idle) < self._max_idle:
                    self._idle.append(conn)
                    return
        self.engine.conn_close(conn)

    def discard(self, conn: int) -> None:
        """Close a handle whose stream state is unknown (request failed)."""
        self.engine.conn_close(conn)

    def note_stale_retry(self) -> None:
        with self._lock:
            self.stats["stale_retries"] += 1
        flight_annotate("retry", reason="stale")

    def run(
        self,
        request: Callable[[int], dict],
        reusable: Callable[[dict], bool] = lambda r: True,
        retry_stale: Callable[[NativeError], bool] = (
            lambda e: e.code not in PERMANENT_CODES
        ),
    ) -> dict:
        """Run one request on a pooled (or fresh) handle.

        On success the handle returns to the idle pool when ``reusable(r)``
        and the pool has room. On :class:`NativeError` the handle is closed
        (stream state unknown); if this was the first use of a POOLED
        handle and ``retry_stale(e)`` holds, the request retries once on a
        fresh connection before the error propagates — the default never
        burns a stale retransmit on permanent protocol errors (TB_EPROTO/
        TB_ETOOBIG/TB_ECHUNKED reproduce identically on a fresh socket);
        callers override it so errors that prove the server answered (an
        explicit grpc-status) are never misread as pool staleness either.
        """
        conn, reused = self.acquire()
        while True:
            try:
                r = request(conn)
            except NativeError as e:
                self.engine.conn_close(conn)
                if reused and retry_stale(e):
                    reused = False
                    self.note_stale_retry()
                    conn = self._new()
                    continue
                raise
            except BaseException:
                # Includes KeyboardInterrupt: an interrupted request must
                # not strand the native connection either.
                self.engine.conn_close(conn)
                raise
            put_back = False
            if reusable(r):
                with self._lock:
                    if len(self._idle) < self._max_idle:
                        self._idle.append(conn)
                        put_back = True
            if not put_back:
                self.engine.conn_close(conn)
            return r

    def close(self) -> None:
        with self._lock:
            conns, self._idle = self._idle, []
        for h in conns:
            self.engine.conn_close(h)
        self.buffers.close()


def fail_unfinished(done: list, errs: list, err: StorageError) -> list:
    """Classify ``err`` onto every unfinished range (the batch readers'
    per-range contract: report, don't throw). Shared by the batch loop's
    fail_all and the backends' setup-failure paths."""
    for i in range(len(done)):
        if not done[i]:
            errs[i] = err
            done[i] = True
    return errs


def run_multiplexed_batch(
    pool: "NativeConnPool",
    n: int,
    done: list,
    errs: list,
    submit: Callable[[int, int], None],
    classify: Callable[[int, dict], object],
    name: str,
    window: int = 16,
    answered: Callable[[NativeError], bool] = lambda e: False,
) -> list:
    """The multiplexed-stream batch loop + stale-retransmit machine, written
    ONCE for both h2-stream batch readers (gRPC ReadObject streams and
    whole-client-http2 ranged GETs — they diverged the moment there were
    two copies; the gRPC twin's answered-guard was structurally missing
    from the http one).

    ``submit(conn, i)`` opens range *i*'s stream on ``conn``;
    ``classify(i, completion)`` maps a completion to ``None`` or a
    classified StorageError; ``answered(e)`` returns True when a
    connection-fatal error PROVES the server answered (e.g. an explicit
    grpc-status) — those must never be retried as pool staleness. Submit
    runs in ``window``-sized waves below the 32-stream connection cap; one
    whole-batch retransmit on a fresh connection when the FIRST use of a
    pooled handle fails before any completion. Fills ``errs`` in place and
    returns it; setup/connect failures classify onto every unfinished
    range (the caller's per-range contract: report, don't throw).
    """

    def fail_all(err: StorageError) -> list:
        return fail_unfinished(done, errs, err)

    try:
        conn, reused = pool.acquire()
    except StorageError as e:
        return fail_all(e)
    except Exception as e:  # noqa: BLE001 — e.g. auth library errors
        return fail_all(
            StorageError(f"read_ranges setup: {e}", transient=True)
        )
    engine = pool.engine
    while True:
        submitted = 0
        completed = 0
        got_any = False
        pending = [i for i in range(n) if not done[i]]
        try:
            while completed < len(pending):
                while (
                    submitted < len(pending)
                    and submitted - completed < window
                ):
                    submit(conn, pending[submitted])
                    submitted += 1
                c = engine.h2_poll(conn)
                if c is None:
                    raise StorageError(
                        f"read_ranges {name}: stream vanished",
                        transient=True,
                    )
                got_any = True
                i = c["tag"]
                errs[i] = classify(i, c)
                done[i] = True
                completed += 1
            pool.release(conn, True)
            return errs
        except NativeError as e:
            pool.discard(conn)
            stale = (
                reused
                and not got_any
                and e.code not in PERMANENT_CODES
                and not answered(e)
            )
            if stale:
                # Whole-batch retransmit on a fresh connection.
                reused = False
                pool.note_stale_retry()
                try:
                    conn = pool.fresh()
                except StorageError as e2:
                    return fail_all(e2)
                continue
            return fail_all(
                StorageError(
                    f"read_ranges {name}: {e}",
                    transient=e.code not in PERMANENT_CODES,
                )
            )
        except StorageError as e:
            pool.discard(conn)
            return fail_all(e)
        except BaseException:
            pool.discard(conn)
            raise
