"""Serve-plane fetches through the reactor executor (ISSUE 19 rung 3).

:class:`ReactorFetchBackend` slots between ``open_backend``'s protocol
switch and the tail/retry wrappers: every ``open_read`` becomes one
ranged GET submitted to a SHARED native fetch pool (the epoll reactor by
default), so serve workers stop burning a Python socket read per chunk —
the fetch hot loop runs on the event loop's thread(s), and N serve
workers multiplex over a handful of keep-alive connections (TLS or h2
included, PR 19's nonblocking state machine).

Contracts kept deliberately narrow:

* the pool is LAZY — a workload that never calls ``open_read`` (the
  read runners drive ``tb_pool_*`` themselves) never spins it up;
* completions land in a per-request ``bytearray`` and the reader serves
  from it; ``generation`` is ``None`` = *unknown* (the engine does not
  surface ``x-goog-generation``), the documented degrade the chunk
  cache already accepts from native transports;
* failures raise :class:`StorageError` with the SAME transient/permanent
  split as the executor runners (engine PERMANENT_CODES + HTTP
  408/429/5xx), so the tail/retry stack above composes unchanged;
* if the native engine is unavailable (or pool creation fails) the
  adapter falls back to the inner backend's Python read path with ONE
  counted warning line — never a silent mislabel.
"""

from __future__ import annotations

import ctypes
import threading
import time
from typing import Optional

from tpubench.storage.base import StorageBackend, StorageError


class _RangeReader:
    """Reader over one completed ranged GET (bytes already in memory)."""

    def __init__(self, data: memoryview, first_byte_ns: Optional[int]):
        self._data = data
        self._off = 0
        self.first_byte_ns = first_byte_ns
        self.generation = None  # engine path: generation unknown

    def readinto(self, buf) -> int:
        mv = memoryview(buf)
        n = min(len(mv), len(self._data) - self._off)
        if n <= 0:
            return 0
        mv[:n] = self._data[self._off:self._off + n]
        self._off += n
        return n

    def close(self) -> None:
        self._data = b""


class _Pending:
    __slots__ = ("event", "completion", "buf", "view")

    def __init__(self, buf: bytearray, view):
        self.event = threading.Event()
        self.completion: Optional[dict] = None
        self.buf = buf      # keepalive: the engine writes into it
        self.view = view    # ctypes view pinning the bytearray exporter


class ReactorFetchBackend:
    """StorageBackend adapter routing ``open_read`` through the native
    fetch pool. Everything else delegates to ``inner`` (a
    ``GcsHttpBackend``)."""

    #: completion wait bound — mirrors the executor runners' 120 s stall
    #: guard; the engine's own 60 s I/O sweep fails tasks well before it.
    WAIT_S = 180.0

    def __init__(self, inner, connections: int = 8, cap: int = 256,
                 mode: str = "reactor"):
        self.inner = inner
        self._connections = connections
        self._cap = cap
        self._mode = mode
        self._pool = None
        self._engine = None
        self._fallback = False
        self._lock = threading.Lock()
        self._pending: dict[int, _Pending] = {}
        self._pending_lock = threading.Lock()
        self._next_tag = 0
        self._sem = threading.Semaphore(cap)
        self._drainer: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.pool_mode: Optional[str] = None  # what actually engaged

    # ------------------------------------------------------ pool plumbing --

    def _ensure_pool(self):
        """Lazy shared pool; returns None when falling back to Python."""
        with self._lock:
            if self._fallback:
                return None
            if self._pool is not None:
                return self._pool
            from tpubench.workloads.fetch_executor import (
                _make_pool,
                warn_fallback,
            )

            reason = ""
            try:
                from tpubench.native.engine import get_engine

                engine = get_engine()
                if engine is None:
                    reason = "native engine unavailable"
            except Exception as e:  # noqa: BLE001
                engine, reason = None, str(e)
            if not reason and not hasattr(self.inner, "native_request_parts"):
                reason = "backend has no native request surface"
            pool = None
            if not reason:
                try:
                    pool = _make_pool(
                        engine, self.inner, self._connections, self._cap,
                        mode=self._mode,
                    )
                except Exception as e:  # noqa: BLE001
                    reason = f"pool creation failed: {e}"
            if pool is None:
                self._fallback = True
                warn_fallback(self._mode, "python", f"serve fetch: {reason}")
                return None
            self._engine = engine
            self._pool = pool
            self.pool_mode = pool.mode
            self._drainer = threading.Thread(
                target=self._drain_loop, name="reactor-fetch-drain",
                daemon=True,
            )
            self._drainer.start()
            return pool

    def _drain_loop(self) -> None:
        # The ONE draining thread (SPSC ring contract); serve workers
        # block on per-tag events, so completion fan-out costs no locks
        # on the ring itself.
        while True:
            cs = self._pool.next_batch(timeout_ms=100)
            for c in cs:
                with self._pending_lock:
                    p = self._pending.get(c["tag"])
                if p is not None:
                    p.completion = c
                    p.event.set()
            if self._stop.is_set() and not cs:
                with self._pending_lock:
                    idle = not self._pending
                if idle:
                    return

    # ---------------------------------------------------------- read path --

    def open_read(self, name: str, start: int = 0,
                  length: Optional[int] = None):
        pool = self._ensure_pool()
        if pool is None:
            return self.inner.open_read(name, start=start, length=length)
        if length is None:
            length = max(0, self.inner.stat(name).size - start)
        if length == 0:
            return _RangeReader(memoryview(b""), None)
        host, port, path, headers = self.inner.native_request_parts(name)
        headers += f"Range: bytes={start}-{start + length - 1}\r\n"
        buf = bytearray(length)
        view = (ctypes.c_char * length).from_buffer(buf)
        p = _Pending(buf, view)
        self._sem.acquire()
        try:
            with self._pending_lock:
                tag = self._next_tag
                self._next_tag += 1
                self._pending[tag] = p
            try:
                pool.submit_to(
                    host, port, path, ctypes.addressof(view), length,
                    headers=headers, tag=tag,
                )
            except Exception:
                with self._pending_lock:
                    self._pending.pop(tag, None)
                raise
            if not p.event.wait(self.WAIT_S):
                # Deliberately LEAVE the pending entry (and its buffer)
                # registered: the engine may still write into the buffer,
                # so dropping the last reference would be a
                # write-after-free. The drainer settles it eventually.
                raise StorageError(
                    f"{name}: reactor fetch timed out after {self.WAIT_S}s",
                    transient=True,
                )
            with self._pending_lock:
                del self._pending[tag]
        finally:
            self._sem.release()
        return self._complete(name, length, p)

    def _complete(self, name: str, length: int, p: _Pending):
        from tpubench.native.engine import PERMANENT_CODES
        from tpubench.storage.gcs_http import _TRANSIENT

        c = p.completion
        result, status = c["result"], c["status"]
        if result < 0:
            raise StorageError(
                f"{name}: engine error {result}",
                transient=result not in PERMANENT_CODES, code=result,
            )
        if status not in (200, 206):
            raise StorageError(
                f"{name}: HTTP {status}",
                transient=status in _TRANSIENT, code=status,
            )
        if result != length:
            raise StorageError(
                f"{name}: ranged GET returned {result} bytes, "
                f"wanted {length}",
                transient=True,
            )
        del p.view  # release the exporter before handing bytes out
        fb = c["first_byte_ns"] or None
        return _RangeReader(memoryview(p.buf), fb)

    # --------------------------------------------------------- delegation --

    def write(self, name, data, if_generation_match=None):
        return self.inner.write(
            name, data, if_generation_match=if_generation_match
        )

    def open_write(self, name, if_generation_match=None):
        return self.inner.open_write(
            name, if_generation_match=if_generation_match
        )

    def list(self, prefix: str = "", page_size: int = 0):
        return self.inner.list(prefix, page_size=page_size)

    def stat(self, name: str):
        return self.inner.stat(name)

    def delete(self, name: str) -> None:
        self.inner.delete(name)

    def close(self) -> None:
        with self._lock:
            pool, drainer = self._pool, self._drainer
            self._pool, self._drainer = None, None
        if drainer is not None:
            self._stop.set()
            drainer.join(timeout=10)
        if pool is not None:
            pool.close()
        self.inner.close()


def maybe_wrap_reactor_fetch(inner, cfg) -> StorageBackend:
    """``open_backend`` hook: route backend reads through the native
    fetch pool when the config asks for a native fetch executor on an
    HTTP backend. Lazy — wrapping costs nothing until ``open_read``."""
    fe = cfg.workload.fetch_executor
    if not fe.startswith("native"):
        return inner
    from tpubench.workloads.fetch_executor import executor_mode

    return ReactorFetchBackend(
        inner,
        connections=max(2, min(16, cfg.serve.workers)),
        cap=max(64, 4 * cfg.serve.workers),
        mode=executor_mode(fe),
    )
