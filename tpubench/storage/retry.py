"""gax-style retry/backoff.

Reference policy (``main.go:40-42,179-184``): ``storage.RetryAlways`` with
``gax.Backoff{Max: 30s, Multiplier: 2.0}``. gax semantics: each pause is a
uniformly random duration in [0, cur] (jitter), after which
``cur = min(cur * multiplier, max)``. We reproduce that, add an optional
attempt cap and deadline (absent in the reference — tests need termination),
and classify retryability via ``StorageError.transient``.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, TypeVar

from tpubench.config import RetryConfig
from tpubench.obs.flight import annotate as _flight_annotate
from tpubench.storage.base import StorageError

T = TypeVar("T")


class Backoff:
    """Stateful pause generator with gax semantics."""

    def __init__(self, cfg: RetryConfig, rng: Optional[random.Random] = None):
        self.cfg = cfg
        self._cur = cfg.initial_backoff_s
        self._rng = rng or random.Random()

    def pause(self) -> float:
        d = self._rng.uniform(0, self._cur) if self.cfg.jitter else self._cur
        self._cur = min(self._cur * self.cfg.multiplier, self.cfg.max_backoff_s)
        return d


def _is_retryable(exc: BaseException, policy: str) -> bool:
    if policy == "never":
        return False
    if policy == "always":
        # RetryAlways (main.go:182): any storage-level failure retries.
        return isinstance(exc, (StorageError, ConnectionError, TimeoutError, OSError))
    # "idempotent": only errors the backend marked transient (503s, resets).
    return isinstance(exc, StorageError) and exc.transient


def retry_call(
    fn: Callable[[], T],
    cfg: RetryConfig,
    *,
    sleep: Callable[[float], None] = time.sleep,
    clock: Callable[[], float] = time.monotonic,
    on_retry: Optional[Callable[[int, BaseException, float], None]] = None,
    rng: Optional[random.Random] = None,
) -> T:
    """Run ``fn`` under the retry policy. ``sleep``/``clock`` are injectable
    for deterministic tests (SURVEY §4 unit prescription)."""
    backoff = Backoff(cfg, rng=rng)
    start = clock()
    attempt = 0
    while True:
        try:
            return fn()
        except BaseException as exc:  # noqa: BLE001 — classified below
            attempt += 1
            if not _is_retryable(exc, cfg.policy):
                raise
            if cfg.max_attempts and attempt >= cfg.max_attempts:
                raise
            pause = backoff.pause()
            if cfg.deadline_s and (clock() - start) + pause > cfg.deadline_s:
                raise
            if on_retry is not None:
                on_retry(attempt, exc, pause)
            # Flight-recorder annotation: the retry becomes part of THIS
            # read's record (no-op when no op is active).
            _flight_annotate(
                "retry", attempt=attempt, error=type(exc).__name__
            )
            sleep(pause)
