"""Client-level retry decorator for any backend.

The reference attaches retry at the *client* (``client.SetRetry``,
main.go:179-184) and the Go storage library transparently restarts an
interrupted download from the current offset. We reproduce both behaviors
uniformly for every backend via this wrapper:

* ``open_read``/metadata ops are retried under the gax policy;
* a reader hit by a transient mid-stream error is re-opened at
  ``start + bytes_already_delivered`` (ranged read) and continues, so the
  caller sees one uninterrupted stream.
"""

from __future__ import annotations

import time
from typing import Optional

from tpubench.config import RetryConfig
from tpubench.storage.base import ObjectMeta, StorageBackend
from tpubench.storage.retry import Backoff, _is_retryable, retry_call


class _ResumingReader:
    def __init__(
        self,
        backend: StorageBackend,
        name: str,
        start: int,
        length: Optional[int],
        retry: RetryConfig,
    ):
        self._backend = backend
        self._name = name
        self._start = start
        self._length = length
        self._retry = retry
        self._delivered = 0
        self.first_byte_ns: Optional[int] = None
        self._inner = retry_call(lambda: backend.open_read(name, start, length), retry)
        self.reopen_count = 0

    def _reopen(self) -> None:
        try:
            self._inner.close()
        except Exception:
            pass
        new_start = self._start + self._delivered
        new_length = None if self._length is None else self._length - self._delivered
        self._inner = retry_call(
            lambda: self._backend.open_read(self._name, new_start, new_length),
            self._retry,
        )
        self.reopen_count += 1

    def readinto(self, buf: memoryview) -> int:
        attempts = 0
        backoff = start = None  # lazily created: the happy path pays nothing
        while True:
            try:
                n = self._inner.readinto(buf)
            except BaseException as exc:  # noqa: BLE001 — classified below
                attempts += 1
                if not _is_retryable(exc, self._retry.policy):
                    raise
                if self._retry.max_attempts and attempts >= self._retry.max_attempts:
                    raise
                # Same bounding as retry_call: gax backoff pause between
                # resume attempts, and deadline_s terminates an otherwise
                # endless resume loop (e.g. 100% injected read faults).
                if backoff is None:
                    backoff = Backoff(self._retry)
                    start = time.monotonic()
                pause = backoff.pause()
                if self._retry.deadline_s and (
                    time.monotonic() - start
                ) + pause > self._retry.deadline_s:
                    raise
                from tpubench.obs.flight import annotate as _flight_annotate

                _flight_annotate(
                    "retry", attempt=attempts, reason="resume",
                    error=type(exc).__name__,
                )
                time.sleep(pause)
                self._reopen()
                continue
            if n > 0 and self.first_byte_ns is None:
                self.first_byte_ns = self._inner.first_byte_ns
            if n > 0:
                self._delivered += n
            return n

    def close(self) -> None:
        self._inner.close()


class RetryingBackend:
    """Wraps any StorageBackend with the reference's client-level retry."""

    def __init__(self, inner: StorageBackend, retry: Optional[RetryConfig] = None):
        self.inner = inner
        self.retry = retry or RetryConfig()

    def open_read(self, name: str, start: int = 0, length: Optional[int] = None):
        return _ResumingReader(self.inner, name, start, length, self.retry)

    def write(self, name: str, data: bytes) -> ObjectMeta:
        return retry_call(lambda: self.inner.write(name, data), self.retry)

    def list(self, prefix: str = "") -> list[ObjectMeta]:
        return retry_call(lambda: self.inner.list(prefix), self.retry)

    def stat(self, name: str) -> ObjectMeta:
        return retry_call(lambda: self.inner.stat(name), self.retry)

    def delete(self, name: str) -> None:
        return retry_call(lambda: self.inner.delete(name), self.retry)

    def close(self) -> None:
        self.inner.close()
