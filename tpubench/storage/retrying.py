"""Client-level retry decorator for any backend.

The reference attaches retry at the *client* (``client.SetRetry``,
main.go:179-184) and the Go storage library transparently restarts an
interrupted download from the current offset. We reproduce both behaviors
uniformly for every backend via this wrapper:

* ``open_read``/metadata ops are retried under the gax policy;
* a reader hit by a transient mid-stream error is re-opened at
  ``start + bytes_already_delivered`` (ranged read) and continues, so the
  caller sees one uninterrupted stream.

The failure budget is *consecutive*: the attempt counter and the
backoff/deadline window reset as soon as bytes flow again, so a long
stream with sporadic-but-recovering transient faults (the chaos plane's
bread and butter) never exhausts ``max_attempts`` — only a fault the
resume path cannot make progress past does. ``rng``/``sleep``/``clock``
are injectable so chaos tests run deterministically without real sleeps.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional

from tpubench.config import RetryConfig
from tpubench.obs.flight import annotate as _flight_annotate
from tpubench.storage.base import ObjectMeta, StorageBackend, StorageError
from tpubench.storage.retry import Backoff, _is_retryable, retry_call


class _ResumingReader:
    def __init__(
        self,
        backend: StorageBackend,
        name: str,
        start: int,
        length: Optional[int],
        retry: RetryConfig,
        *,
        rng: Optional[random.Random] = None,
        sleep: Callable[[float], None] = time.sleep,
        clock: Callable[[], float] = time.monotonic,
    ):
        self._backend = backend
        self._name = name
        self._start = start
        self._length = length
        self._retry = retry
        self._rng = rng
        self._sleep = sleep
        self._clock = clock
        self._delivered = 0
        self.first_byte_ns: Optional[int] = None
        # Consecutive-failure state: persists across readinto calls while
        # no bytes flow, resets on progress (see module docstring).
        self._attempts = 0
        self._backoff: Optional[Backoff] = None
        self._window_start: Optional[float] = None
        self._inner = retry_call(
            lambda: backend.open_read(name, start, length), retry,
            sleep=sleep, clock=clock, rng=rng,
        )
        self.reopen_count = 0

    @property
    def generation(self):
        """Served object's generation when the transport surfaces it
        (see ObjectReader protocol) — forwarded from the CURRENT inner
        reader, so a resume that lands on a different generation is
        visible to cache-invalidation consumers."""
        return getattr(self._inner, "generation", None)

    def _reopen(self) -> None:
        try:
            self._inner.close()
        except Exception:
            pass
        new_start = self._start + self._delivered
        new_length = None if self._length is None else self._length - self._delivered
        self._inner = retry_call(
            lambda: self._backend.open_read(self._name, new_start, new_length),
            self._retry,
            sleep=self._sleep, clock=self._clock, rng=self._rng,
        )
        self.reopen_count += 1

    def readinto(self, buf: memoryview) -> int:
        while True:
            try:
                n = self._inner.readinto(buf)
            except BaseException as exc:  # noqa: BLE001 — classified below
                self._attempts += 1
                if not _is_retryable(exc, self._retry.policy):
                    raise
                if self._retry.max_attempts and (
                    self._attempts >= self._retry.max_attempts
                ):
                    raise
                # Same bounding as retry_call: gax backoff pause between
                # resume attempts, and deadline_s terminates an otherwise
                # endless zero-progress resume loop (e.g. 100% injected
                # read faults). Lazily created: the happy path pays
                # nothing; discarded again once bytes flow.
                if self._backoff is None:
                    self._backoff = Backoff(self._retry, rng=self._rng)
                    self._window_start = self._clock()
                pause = self._backoff.pause()
                if self._retry.deadline_s and (
                    self._clock() - self._window_start
                ) + pause > self._retry.deadline_s:
                    raise
                # backoff_s rides the note so the trace plane can
                # synthesize the retry attempt as a child SPAN covering
                # its pause (obs/trace.py), not just a point event.
                _flight_annotate(
                    "retry", attempt=self._attempts, reason="resume",
                    error=type(exc).__name__, backoff_s=round(pause, 6),
                )
                self._sleep(pause)
                self._reopen()
                continue
            if n > 0 and self.first_byte_ns is None:
                self.first_byte_ns = self._inner.first_byte_ns
            if n > 0:
                self._delivered += n
                if self._attempts:
                    # Bytes flow again: every fault so far recovered, so
                    # the NEXT fault gets the full gax allowance (fresh
                    # counter, fresh backoff progression, fresh deadline
                    # window) instead of the leftovers.
                    self._attempts = 0
                    self._backoff = None
                    self._window_start = None
            return n

    def close(self) -> None:
        self._inner.close()


class _ResumingWriter:
    """The write-path twin of :class:`_ResumingReader`: a resumable
    upload whose part sends ride the gax policy. A transient mid-part
    failure re-probes the server's committed offset (the 308-with-Range
    resume query) and resends only the tail; the consecutive-failure
    budget resets whenever committed bytes ADVANCE, so a long upload
    with sporadic-but-recovering faults never exhausts ``max_attempts``
    — only a fault the resume cannot make progress past does.
    ``resumed_parts`` counts parts that needed at least one resume (the
    ckpt-save scorecard's resumed-part count)."""

    def __init__(
        self,
        backend: StorageBackend,
        name: str,
        if_generation_match,
        retry: RetryConfig,
        *,
        rng: Optional[random.Random] = None,
        sleep: Callable[[float], None] = time.sleep,
        clock: Callable[[], float] = time.monotonic,
    ):
        # ``retry`` arrives already pinned to transient-only
        # classification by RetryingBackend._write_retry (the one
        # definition of the write-path policy pin).
        self._retry = retry
        self._rng = rng
        self._sleep = sleep
        self._clock = clock
        self._inner = retry_call(
            lambda: backend.open_write(
                name, if_generation_match=if_generation_match
            ),
            retry, sleep=sleep, clock=clock, rng=rng,
        )
        self.name = name
        self.resumed_parts = 0

    @property
    def offset(self) -> int:
        return self._inner.offset

    def write(self, data) -> int:
        mv = memoryview(data)
        if mv.ndim != 1 or mv.itemsize != 1:
            mv = mv.cast("B")
        base = self._inner.offset
        end = base + len(mv)
        attempts = 0
        backoff: Optional[Backoff] = None
        window_start: Optional[float] = None
        best = base  # highest committed offset observed (progress marker)
        resumed = False
        while True:
            try:
                off = self._inner.offset
                if off < base:
                    # The server's watermark regressed past this part's
                    # start: the missing bytes belong to an EARLIER part
                    # this call no longer holds — unrecoverable here.
                    raise StorageError(
                        f"upload {self.name}: committed {off} regressed "
                        f"past part start {base}", transient=False,
                    )
                if off < end:
                    self._inner.write(mv[off - base:])
                if resumed:
                    self.resumed_parts += 1
                return self._inner.offset
            except BaseException as exc:  # noqa: BLE001 — classified below
                attempts += 1
                if not _is_retryable(exc, self._retry.policy):
                    raise
                if self._retry.max_attempts and (
                    attempts >= self._retry.max_attempts
                ):
                    raise
                if backoff is None:
                    backoff = Backoff(self._retry, rng=self._rng)
                    window_start = self._clock()
                pause = backoff.pause()
                if self._retry.deadline_s and (
                    self._clock() - window_start
                ) + pause > self._retry.deadline_s:
                    raise
                _flight_annotate(
                    "retry", attempt=attempts, reason="upload_resume",
                    error=type(exc).__name__, backoff_s=round(pause, 6),
                )
                self._sleep(pause)
                resumed = True
                try:
                    committed = self._inner.committed()
                except Exception:  # noqa: BLE001 — probe failure: the
                    committed = None  # next loop iteration burns budget
                if committed is not None and committed > best:
                    # Bytes landed since the last look: the fault
                    # recovered, so the NEXT one gets the full allowance.
                    best = committed
                    attempts = 0
                    backoff = None
                    window_start = None

    def committed(self) -> int:
        return retry_call(
            self._inner.committed, self._retry,
            sleep=self._sleep, clock=self._clock, rng=self._rng,
        )

    def finalize(self) -> ObjectMeta:
        # Safe under retry: every backend's finalize is idempotent by
        # contract (a completed session replays its stored meta), and a
        # 412 precondition mismatch is non-transient — never retried.
        return retry_call(
            self._inner.finalize, self._retry,
            sleep=self._sleep, clock=self._clock, rng=self._rng,
        )

    def abort(self) -> None:
        self._inner.abort()


class RetryingBackend:
    """Wraps any StorageBackend with the reference's client-level retry.

    ``rng``/``sleep``/``clock`` flow through to every retry loop (open
    retries AND mid-stream resumes) for deterministic chaos tests."""

    def __init__(
        self,
        inner: StorageBackend,
        retry: Optional[RetryConfig] = None,
        *,
        rng: Optional[random.Random] = None,
        sleep: Callable[[float], None] = time.sleep,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.inner = inner
        self.retry = retry or RetryConfig()
        self._rng = rng
        self._sleep = sleep
        self._clock = clock

    def _call(self, fn):
        return retry_call(
            fn, self.retry, sleep=self._sleep, clock=self._clock, rng=self._rng
        )

    def _write_retry(self) -> RetryConfig:
        """The ONE write-path policy pin (write + open_write): the
        reference's RetryAlways (main.go:182) is its READ policy — "any
        storage failure retries" is safe when the remedy is re-reading.
        On the write path a non-transient 412 precondition mismatch (or
        a 400 offset bug) reproduces on every replay, so retrying it
        forever would turn the idempotency anchor into a livelock —
        transient-only classification is the only correct behavior."""
        if self.retry.policy != "always":
            return self.retry
        import dataclasses

        return dataclasses.replace(self.retry, policy="idempotent")

    def open_read(self, name: str, start: int = 0, length: Optional[int] = None):
        return _ResumingReader(
            self.inner, name, start, length, self.retry,
            rng=self._rng, sleep=self._sleep, clock=self._clock,
        )

    def write(self, name: str, data: bytes,
              if_generation_match=None) -> ObjectMeta:
        return retry_call(
            lambda: self.inner.write(
                name, data, if_generation_match=if_generation_match
            ),
            self._write_retry(),
            sleep=self._sleep, clock=self._clock, rng=self._rng,
        )

    def open_write(self, name: str, if_generation_match=None):
        return _ResumingWriter(
            self.inner, name, if_generation_match, self._write_retry(),
            rng=self._rng, sleep=self._sleep, clock=self._clock,
        )

    def list(self, prefix: str = "", page_size: int = 0) -> list[ObjectMeta]:
        return self._call(lambda: self.inner.list(prefix, page_size=page_size))

    def stat(self, name: str) -> ObjectMeta:
        return self._call(lambda: self.inner.stat(name))

    def delete(self, name: str) -> None:
        return self._call(lambda: self.inner.delete(name))

    def close(self) -> None:
        self.inner.close()
