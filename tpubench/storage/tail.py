"""Tail-tolerance layer: stall watchdog, hedged reads, circuit breaker.

The paper's north star makes the POD the unit under test, so one silently
slow stream sets the p99 for every chip. The reference can only
retry-after-FAILURE (gax, ``main.go:179-184``); this module adds the three
standard tail-tolerance mechanisms (Dean & Barroso, "The Tail at Scale")
as composable :class:`~tpubench.storage.base.StorageBackend` wrappers:

* :class:`WatchdogBackend` — a **stall watchdog** per reader: a stream
  whose throughput stays below ``stall_floor_bps`` for at least
  ``stall_window_s`` is cancelled with a transient :class:`StallError`,
  which the resume path in :mod:`tpubench.storage.retrying` picks up and
  reopens at offset. Clock injectable → deterministic tests.
* :class:`HedgedBackend` — **hedged reads**: if the first byte hasn't
  arrived within the hedge delay (fixed, or derived from the run's
  rolling p99 first-byte latency), a second ranged read for the same
  bytes races the first; the winner streams, the loser is cancelled and
  its bytes counted as waste. The hedged reader ALSO runs the stall
  watchdog asynchronously (queue timeouts), so it detects a blackholed
  stream even while the producer thread is blocked inside a socket read
  — the one stall shape a same-thread boundary check can never see.
* :class:`BreakerBackend` — a per-backend **circuit breaker**
  (closed → open → half-open with probes): an endpoint that keeps
  failing is shed with a transient :class:`CircuitOpenError` instead of
  being hammered; after ``breaker_reset_s`` a limited probe set decides
  whether to close again. Composes under :class:`RetryingBackend` —
  shed opens are retried under the same gax pacing.

Stack order (built by ``open_backend``):
``Retrying( Hedged( Watchdog( Breaker( inner ))))`` with each layer
optional. Every hedge/stall/breaker event is annotated onto the calling
thread's flight-recorder op, so ``tpubench report timeline`` attributes
them per read.

Known limit: hedge cancellation is COOPERATIVE (the loser closes its own
reader at the next chunk boundary — no cross-thread close races the
backend). A producer blocked inside ``readinto`` (a blackholed socket,
the fake's ``stall_s``) therefore lingers as a daemon thread, holding
one chunk buffer, until the read unblocks or the process exits. Under a
sustained blackhole fault each rescued read can strand a thread for the
fault's duration — size blackhole chaos runs accordingly (bounded read
counts, or a finite ``stall_s``).
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from typing import Callable, Optional

from tpubench.config import TailConfig
from tpubench.obs.flight import annotate as _flight_annotate
from tpubench.storage.base import StorageBackend, StorageError


class StallError(StorageError):
    """A stream cancelled by the stall watchdog. Transient by contract:
    the resume path reopens the read at the delivered offset."""

    def __init__(self, msg: str):
        super().__init__(msg, transient=True, code=598)


class CircuitOpenError(StorageError):
    """Open shed by an OPEN circuit breaker — transient (the endpoint may
    recover), so the retry policy paces re-attempts instead of the caller
    hammering a known-bad endpoint."""

    def __init__(self, msg: str):
        super().__init__(msg, transient=True, code=503)


class _WrapperBackend:
    """Delegating base for the tail wrappers: everything but open_read
    passes straight through; ``inner`` is public so stats collectors and
    diagnostics can walk the chain."""

    def __init__(self, inner: StorageBackend):
        self.inner = inner

    def write(self, name: str, data: bytes, if_generation_match=None):
        return self.inner.write(
            name, data, if_generation_match=if_generation_match
        )

    def open_write(self, name: str, if_generation_match=None):
        # The tail layers shape READS (hedge/watchdog race byte streams);
        # the write path passes through and composes with the retry
        # decorator's resuming writer above this stack.
        return self.inner.open_write(
            name, if_generation_match=if_generation_match
        )

    def list(self, prefix: str = "", page_size: int = 0):
        return self.inner.list(prefix, page_size=page_size)

    def stat(self, name: str):
        return self.inner.stat(name)

    def delete(self, name: str) -> None:
        return self.inner.delete(name)

    def close(self) -> None:
        self.inner.close()


# ------------------------------------------------------------ breaker -----


class Admission:
    """Result of :meth:`CircuitBreaker.allow`: truthiness = admitted,
    ``probe`` = this operation is a half-open probe whose outcome must be
    settled (shared immutable singletons — allocation-free hot path)."""

    __slots__ = ("allowed", "probe")

    def __init__(self, allowed: bool, probe: bool):
        self.allowed = allowed
        self.probe = probe

    def __bool__(self) -> bool:
        return self.allowed


_ADMIT = Admission(True, False)
_PROBE = Admission(True, True)
_SHED = Admission(False, False)


class CircuitBreaker:
    """Closed → open → half-open state machine over consecutive failures.

    Thread-safe; ``clock`` injectable for deterministic tests. ``open``
    time is accumulated into the stats so the resilience scorecard can
    report how long the endpoint was shed."""

    def __init__(
        self,
        failures: int = 5,
        reset_s: float = 5.0,
        probes: int = 1,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.failures_to_open = max(1, failures)
        self.reset_s = reset_s
        self.probes_to_close = max(1, probes)
        self._clock = clock
        self._lock = threading.Lock()
        self.state = "closed"
        self._consecutive = 0
        self._opened_at: Optional[float] = None
        self._probes_inflight = 0
        self._probes_ok = 0
        self._open_s_total = 0.0
        self.opens = 0
        self.shed = 0
        self.probes = 0

    def allow(self) -> "Admission":
        """May a new operation proceed right now? The admission is falsy
        when shed; ``admission.probe`` marks a half-open probe, whose
        outcome MUST be settled (``record_success``/``record_failure``
        with ``probe=True``, or :meth:`abandon_probe`) — a leaked probe
        slot would shed every subsequent open forever."""
        with self._lock:
            now = self._clock()
            if self.state == "open":
                if now - self._opened_at < self.reset_s:
                    self.shed += 1
                    return _SHED
                # Cooldown elapsed: half-open, admit a probe set.
                self._open_s_total += now - self._opened_at
                self._opened_at = None
                self.state = "half_open"
                self._probes_inflight = 0
                self._probes_ok = 0
                _flight_annotate("breaker", state="half_open")
            if self.state == "half_open":
                if self._probes_inflight >= self.probes_to_close:
                    self.shed += 1
                    return _SHED
                self._probes_inflight += 1
                self.probes += 1
                return _PROBE
            return _ADMIT

    def abandon_probe(self) -> None:
        """Release a probe slot whose stream was closed without a
        verdict (cancelled hedge loser, caller closed early): the slot
        frees for the next probe, deciding nothing."""
        with self._lock:
            if self.state == "half_open" and self._probes_inflight > 0:
                self._probes_inflight -= 1

    def record_success(self, probe: bool = False) -> None:
        with self._lock:
            if probe and self.state == "half_open":
                self._probes_inflight -= 1
                self._probes_ok += 1
                if self._probes_ok >= self.probes_to_close:
                    self.state = "closed"
                    self._consecutive = 0
                    _flight_annotate("breaker", state="closed")
            else:
                # Probe verdicts arriving after the state moved on decide
                # nothing (allow() resets the slot counters on the next
                # open -> half-open transition).
                self._consecutive = 0

    def record_failure(self, probe: bool = False) -> None:
        with self._lock:
            now = self._clock()
            if probe and self.state == "half_open":
                self._probes_inflight -= 1
                self._open(now)
                return
            self._consecutive += 1
            if self.state == "closed" and (
                self._consecutive >= self.failures_to_open
            ):
                self._open(now)

    def _open(self, now: float) -> None:
        if self.state != "open":
            self.state = "open"
            self.opens += 1
            self._opened_at = now
            _flight_annotate("breaker", state="open")

    def open_seconds(self) -> float:
        """Total time spent open, INCLUDING the current open stretch."""
        with self._lock:
            total = self._open_s_total
            if self.state == "open" and self._opened_at is not None:
                total += self._clock() - self._opened_at
            return total

    def snapshot(self) -> dict:
        return {
            "state": self.state,
            "opens": self.opens,
            "open_s": self.open_seconds(),
            "shed": self.shed,
            "probes": self.probes,
        }


class _BreakerReader:
    """Reader that reports its outcome to the breaker: clean EOF =
    success, any exception = failure (reported once). A reader closed
    WITHOUT a verdict still settles: delivered bytes count as success
    (ranged reads often close at exactly-length without a 0-byte EOF
    read), a byteless close releases any probe slot undecided — a
    half-open probe must never leak its slot, or the breaker sheds
    every subsequent open forever."""

    def __init__(self, inner, breaker: CircuitBreaker, probe: bool):
        self._inner = inner
        self._breaker = breaker
        self._probe = probe
        self._settled = False
        self._delivered = 0

    @property
    def first_byte_ns(self):
        return self._inner.first_byte_ns

    @property
    def generation(self):
        return getattr(self._inner, "generation", None)

    def readinto(self, buf: memoryview) -> int:
        try:
            n = self._inner.readinto(buf)
        except BaseException:
            if not self._settled:
                self._settled = True
                self._breaker.record_failure(probe=self._probe)
            raise
        if n > 0:
            self._delivered += n
        elif not self._settled:
            self._settled = True
            self._breaker.record_success(probe=self._probe)
        return n

    def close(self) -> None:
        if not self._settled:
            self._settled = True
            if self._delivered > 0:
                self._breaker.record_success(probe=self._probe)
            elif self._probe:
                self._breaker.abandon_probe()
        self._inner.close()


class BreakerBackend(_WrapperBackend):
    def __init__(
        self,
        inner: StorageBackend,
        tail: TailConfig,
        clock: Callable[[], float] = time.monotonic,
    ):
        super().__init__(inner)
        self.breaker = CircuitBreaker(
            failures=tail.breaker_failures,
            reset_s=tail.breaker_reset_s,
            probes=tail.breaker_probes,
            clock=clock,
        )

    def open_read(self, name: str, start: int = 0, length: Optional[int] = None):
        adm = self.breaker.allow()
        if not adm:
            _flight_annotate("breaker", event="shed")
            raise CircuitOpenError(
                f"circuit open: shedding read of {name!r} "
                f"(state={self.breaker.state})"
            )
        try:
            r = self.inner.open_read(name, start, length)
        except BaseException:
            self.breaker.record_failure(probe=adm.probe)
            raise
        return _BreakerReader(r, self.breaker, probe=adm.probe)


# ----------------------------------------------------------- watchdog -----


class WatchdogReader:
    """Boundary-based stall watchdog: the rolling window accumulates only
    time spent INSIDE ``readinto`` (waiting on the stream) — a consumer
    that pauses between calls (a staging sink draining a device_put) is
    never mistaken for a slow stream. A window of in-stream time whose
    throughput is below the floor cancels the stream with
    :class:`StallError`. Detects slow-drip streams; a stream that blocks
    indefinitely inside ONE readinto is invisible to a same-thread check
    — that shape is covered by the hedged reader's async watchdog."""

    def __init__(
        self,
        inner,
        window_s: float,
        floor_bps: float,
        clock: Callable[[], float] = time.monotonic,
        on_stall: Optional[Callable[[], None]] = None,
    ):
        self._inner = inner
        self._window = max(1e-9, window_s)
        self._floor = floor_bps
        self._clock = clock
        self._on_stall = on_stall
        self._win_busy = 0.0  # seconds spent inside inner.readinto
        self._win_bytes = 0

    @property
    def first_byte_ns(self):
        return self._inner.first_byte_ns

    @property
    def generation(self):
        return getattr(self._inner, "generation", None)

    def readinto(self, buf: memoryview) -> int:
        t0 = self._clock()
        n = self._inner.readinto(buf)
        if n <= 0:
            return n  # EOF is never a stall
        self._win_busy += self._clock() - t0
        self._win_bytes += n
        if self._win_busy >= self._window:
            rate = self._win_bytes / self._win_busy
            if rate < self._floor:
                if self._on_stall is not None:
                    self._on_stall()
                _flight_annotate(
                    "stall", rate_bps=int(rate), window_s=self._win_busy,
                    floor_bps=self._floor,
                )
                try:
                    self._inner.close()
                except Exception:  # noqa: BLE001 — already failing the stream
                    pass
                raise StallError(
                    f"stream stalled: {rate:.0f} B/s over "
                    f"{self._win_busy:.2f}s of stream time "
                    f"(floor {self._floor:.0f} B/s)"
                )
            self._win_busy = 0.0
            self._win_bytes = 0
        return n

    def close(self) -> None:
        self._inner.close()


class WatchdogBackend(_WrapperBackend):
    def __init__(
        self,
        inner: StorageBackend,
        tail: TailConfig,
        clock: Callable[[], float] = time.monotonic,
    ):
        super().__init__(inner)
        self.tail = tail
        self._clock = clock
        self._lock = threading.Lock()
        self.stalls = 0

    def _note_stall(self) -> None:
        with self._lock:
            self.stalls += 1

    def open_read(self, name: str, start: int = 0, length: Optional[int] = None):
        return WatchdogReader(
            self.inner.open_read(name, start, length),
            window_s=self.tail.stall_window_s,
            floor_bps=self.tail.stall_floor_bps,
            clock=self._clock,
            on_stall=self._note_stall,
        )


# ------------------------------------------------------------- hedged -----

_HEDGE_CHUNK = 256 * 1024
_ATTEMPT_DEPTH = 4  # chunks a producer may buffer ahead of the consumer
_CANCEL_POLL_S = 0.05


class _Attempt:
    """One racing read: a producer thread that opens the range and pumps
    chunks into the shared queue under a credit cap. Cancellation is
    cooperative — the producer checks the flag at every boundary and
    closes its own reader, so no cross-thread close races the backend."""

    __slots__ = (
        "idx", "open_fn", "out_q", "chunk_bytes", "cancelled", "credits",
        "bytes", "first_byte_ns", "generation", "op", "ctx", "thread",
    )

    def __init__(self, idx: int, open_fn, out_q: "queue.Queue",
                 chunk_bytes: int = _HEDGE_CHUNK):
        self.idx = idx
        self.open_fn = open_fn
        self.out_q = out_q
        self.chunk_bytes = chunk_bytes
        self.cancelled = threading.Event()
        self.credits = threading.Semaphore(_ATTEMPT_DEPTH)
        self.bytes = 0
        self.first_byte_ns: Optional[int] = None
        # Producer-written once post-open, consumer-read post-race
        # (GIL-atomic attribute, same discipline as first_byte_ns).
        self.generation = None
        # The consumer thread's flight op AND trace position (captured
        # at launch): the producer adopts both, so backend-level phases/
        # annotations (connect, first_byte, breaker/retry events) still
        # attribute to the read's record, and any span the leg's backend
        # stack opens parents under the read — despite running on a
        # helper thread. The trace context is captured separately
        # because a hedge can race a read that has a tracer span but no
        # flight op (flight recorder off).
        from tpubench.obs.flight import current_op
        from tpubench.obs.tracing import current_trace

        self.op = current_op()
        self.ctx = current_trace()
        self.thread = threading.Thread(
            target=self._run, daemon=True, name=f"hedge-{idx}"
        )
        self.thread.start()

    def _run(self) -> None:
        from tpubench.obs.flight import adopt_op
        from tpubench.obs.tracing import adopt_trace

        adopt_op(self.op)
        if self.op is None:
            adopt_trace(self.ctx)
        try:
            reader = self.open_fn()
        except BaseException as e:  # noqa: BLE001 — surfaced to the consumer
            self.out_q.put((self.idx, "err", e))
            return
        self.generation = getattr(reader, "generation", None)
        try:
            while not self.cancelled.is_set():
                while not self.credits.acquire(timeout=_CANCEL_POLL_S):
                    if self.cancelled.is_set():
                        return
                buf = bytearray(self.chunk_bytes)
                try:
                    n = reader.readinto(memoryview(buf))
                except BaseException as e:  # noqa: BLE001
                    self.out_q.put((self.idx, "err", e))
                    return
                if self.first_byte_ns is None:
                    self.first_byte_ns = getattr(reader, "first_byte_ns", None)
                if n <= 0:
                    self.out_q.put((self.idx, "eof", None))
                    return
                self.bytes += n
                self.out_q.put((self.idx, "data", memoryview(buf)[:n]))
        finally:
            try:
                reader.close()
            except Exception:  # noqa: BLE001 — best-effort cleanup
                pass

    def cancel(self) -> None:
        self.cancelled.set()


class HedgedReader:
    """Winner-take-all racing reader over the inner backend.

    The primary attempt starts immediately; if no byte has arrived by the
    hedge delay, a second attempt races it for the SAME range from
    scratch (nothing has been delivered yet, so no bytes duplicate). The
    first attempt to produce data wins and streams; the loser is
    cancelled and its bytes are waste. Because the consumer waits on a
    queue, stall detection is asynchronous: no data for a full stall
    window (throughput below the floor) raises :class:`StallError` even
    while the producers are blocked inside socket reads."""

    def __init__(self, hb: "HedgedBackend", name: str, start: int,
                 length: Optional[int]):
        self._hb = hb
        self._name = name
        self._start = start
        self._length = length
        self._q: queue.Queue = queue.Queue()
        self._attempts: list[_Attempt] = []
        self._winner: Optional[_Attempt] = None
        self._errors: dict[int, BaseException] = {}
        self._pending: deque = deque()
        self._eof = False
        self._closed = False
        self.first_byte_ns: Optional[int] = None
        t = hb.tail
        self._opened_t = hb._clock()
        self._hedge_at: Optional[float] = (
            self._opened_t + hb.hedge_delay() if t.hedge else None
        )
        self._watch = t.watchdog
        self._win_start = self._opened_t
        self._win_bytes = 0
        self._launch()

    def _launch(self) -> None:
        idx = len(self._attempts)
        self._attempts.append(_Attempt(
            idx,
            lambda: self._hb.inner.open_read(
                self._name, self._start, self._length
            ),
            self._q,
            chunk_bytes=self._hb.chunk_bytes,
        ))

    # ------------------------------------------------------- internals --
    def _deadline(self) -> Optional[float]:
        dl = None
        if self._hedge_at is not None and self._winner is None:
            dl = self._hedge_at
        if self._watch:
            stall_at = self._win_start + self._hb.tail.stall_window_s
            dl = stall_at if dl is None else min(dl, stall_at)
        return dl

    def _fail(self, exc: BaseException) -> None:
        self.close()
        raise exc

    def _check_stall(self, now: float) -> None:
        if not self._watch:
            return
        elapsed = now - self._win_start
        window = self._hb.tail.stall_window_s
        if elapsed < window:
            return
        rate = self._win_bytes / elapsed if elapsed > 0 else 0.0
        if rate < self._hb.tail.stall_floor_bps:
            self._hb.note_stall()
            _flight_annotate(
                "stall", rate_bps=int(rate), window_s=elapsed,
                floor_bps=self._hb.tail.stall_floor_bps, hedged=True,
            )
            self._fail(StallError(
                f"hedged stream stalled: {rate:.0f} B/s over "
                f"{elapsed:.2f}s window "
                f"(floor {self._hb.tail.stall_floor_bps:.0f} B/s)"
            ))
        self._win_start = now
        self._win_bytes = 0

    def _maybe_hedge(self, now: float) -> None:
        if self._hedge_at is None or self._winner is not None:
            return
        if now < self._hedge_at:
            return
        self._hedge_at = None
        delay = now - self._opened_t
        self._hb.note_hedge_launched()
        _flight_annotate("hedge", event="launch", delay_s=round(delay, 6))
        self._launch()

    def _set_winner(self, att: _Attempt) -> None:
        self._winner = att
        hedged = len(self._attempts) > 1
        if hedged:
            if att.idx > 0:
                self._hb.note_hedge_result(win=True)
                _flight_annotate("hedge", event="win")
            else:
                self._hb.note_hedge_result(win=False)
                _flight_annotate("hedge", event="lose")
        for other in self._attempts:
            if other is not att:
                other.cancel()
        self.first_byte_ns = att.first_byte_ns
        if self.first_byte_ns is None:
            self.first_byte_ns = time.perf_counter_ns()
        self._hb.note_first_byte(self._hb._clock() - self._opened_t)

    @property
    def generation(self):
        att = self._winner or (self._attempts[0] if self._attempts else None)
        return att.generation if att is not None else None

    # ------------------------------------------------------ ObjectReader --
    def readinto(self, buf: memoryview) -> int:
        if self._pending:
            return self._copy_out(buf)
        if self._eof or self._closed:
            return 0
        # Fresh stall window per call: only time spent waiting in THIS
        # call counts toward the stall verdict — a caller that paused
        # between readintos (a staging sink draining a device_put) must
        # not be mistaken for a stalled stream. A genuine stall blocks
        # right here, so the window still elapses within one call.
        self._win_start = self._hb._clock()
        self._win_bytes = 0
        while True:
            now = self._hb._clock()
            self._maybe_hedge(now)
            self._check_stall(now)
            dl = self._deadline()
            timeout = None if dl is None else max(0.001, dl - now)
            try:
                idx, kind, payload = self._q.get(timeout=timeout)
            except queue.Empty:
                continue  # re-evaluate deadlines (hedge launch / stall)
            att = self._attempts[idx]
            if self._winner is None:
                if kind == "err":
                    self._errors[idx] = payload
                    # An attempt died before any byte: if a sibling is
                    # still racing, let it run; once every launched
                    # attempt is dead, surface the error — failure
                    # recovery belongs to the retry layer above, not to
                    # a hedge against a failing endpoint.
                    live = [
                        a for a in self._attempts
                        if a.idx not in self._errors
                    ]
                    if not live:
                        self._fail(payload)
                    continue
                self._set_winner(att)
            if att is not self._winner:
                continue  # loser traffic: dropped (waste counted at close)
            if kind == "data":
                att.credits.release()
                self._win_bytes += len(payload)
                self._pending.append(payload)
                return self._copy_out(buf)
            if kind == "eof":
                self._eof = True
                return 0
            self._fail(payload)  # winner mid-stream error: propagate

    def _copy_out(self, buf: memoryview) -> int:
        chunk = self._pending[0]
        n = min(len(buf), len(chunk))
        buf[:n] = chunk[:n]
        if n == len(chunk):
            self._pending.popleft()
        else:
            self._pending[0] = chunk[n:]
        return n

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for att in self._attempts:
            att.cancel()
        wasted = sum(
            a.bytes for a in self._attempts if a is not self._winner
        )
        if wasted:
            self._hb.note_waste(wasted)


class HedgedBackend(_WrapperBackend):
    """Hedged-read wrapper; also the home of the run's rolling first-byte
    latency samples (the adaptive hedge delay) and the hedge stats."""

    def __init__(
        self,
        inner: StorageBackend,
        tail: TailConfig,
        clock: Callable[[], float] = time.monotonic,
        chunk_bytes: int = _HEDGE_CHUNK,
    ):
        super().__init__(inner)
        self.tail = tail
        self._clock = clock
        # Producer chunk size. Matches the workload's granule when built
        # via open_backend, so hedging does not change the read's
        # granule-pacing semantics (paced fakes meter per call).
        self.chunk_bytes = max(1, chunk_bytes)
        self._lock = threading.Lock()
        self._fb_samples: deque = deque(maxlen=512)
        self._fb_p99: Optional[float] = None
        self._fb_since_p99 = 0
        # Live override of the fixed delay (the tune controller's
        # hedge-delay actuation): replaces tail.hedge_delay_s inside
        # hedge_delay() without mutating shared config; the rolling-p99
        # adaptive path still floors at it, exactly as it floors at the
        # configured fixed delay.
        self._delay_override: Optional[float] = None
        self.stats = {
            "reads": 0,
            "hedges": 0,
            "hedge_wins": 0,
            "hedge_losses": 0,
            "wasted_bytes": 0,
            "stalls": 0,
        }

    # ------------------------------------------------------------ stats --
    def note_first_byte(self, seconds: float) -> None:
        with self._lock:
            self._fb_samples.append(seconds)
            self._fb_since_p99 += 1
            # Refresh the cached p99 every N samples instead of sorting
            # the whole window on every open (the hedge-delay hot path).
            if self._fb_p99 is None or self._fb_since_p99 >= 16:
                self._fb_since_p99 = 0
                if len(self._fb_samples) >= 8:
                    samples = sorted(self._fb_samples)
                    self._fb_p99 = samples[
                        min(len(samples) - 1, int(0.99 * len(samples)))
                    ]

    def note_hedge_launched(self) -> None:
        with self._lock:
            self.stats["hedges"] += 1

    def note_hedge_result(self, win: bool) -> None:
        with self._lock:
            self.stats["hedge_wins" if win else "hedge_losses"] += 1

    def note_waste(self, nbytes: int) -> None:
        with self._lock:
            self.stats["wasted_bytes"] += nbytes

    def note_stall(self) -> None:
        with self._lock:
            self.stats["stalls"] += 1

    def set_hedge_delay(self, seconds: float) -> None:
        """Live fixed-delay override (tune controller actuation)."""
        with self._lock:
            self._delay_override = max(0.0, float(seconds))

    def hedge_delay(self) -> float:
        """The delay before a hedge launches: fixed (or its live tune
        override), or the cached p99(first-byte) × scale once enough
        samples exist (floored at the fixed delay so a cold cache can't
        hedge-storm)."""
        t = self.tail
        with self._lock:
            base = (
                self._delay_override
                if self._delay_override is not None else t.hedge_delay_s
            )
            p99 = self._fb_p99
        if t.hedge_from_p99 and p99 is not None:
            return max(base, p99 * t.hedge_p99_scale)
        return base

    def open_read(self, name: str, start: int = 0, length: Optional[int] = None):
        with self._lock:
            self.stats["reads"] += 1
        return HedgedReader(self, name, start, length)


# ------------------------------------------------------------ assembly ----


def wrap_tail(
    inner: StorageBackend,
    tail: Optional[TailConfig],
    clock: Callable[[], float] = time.monotonic,
    chunk_bytes: int = _HEDGE_CHUNK,
) -> StorageBackend:
    """Compose the configured tail-tolerance wrappers around ``inner``
    (innermost breaker → watchdog → hedging outermost). With hedging on,
    stall detection runs inside the hedged reader (async, catches
    blackholes); standalone, it runs at readinto boundaries."""
    if tail is None or not tail.active:
        return inner
    b = inner
    if tail.breaker:
        b = BreakerBackend(b, tail, clock=clock)
    if tail.watchdog and not tail.hedge:
        b = WatchdogBackend(b, tail, clock=clock)
    if tail.hedge:
        b = HedgedBackend(b, tail, clock=clock, chunk_bytes=chunk_bytes)
    return b


def find_tail_layer(backend, cls):
    """First wrapper of type ``cls`` in the backend's ``.inner`` chain,
    or None — how the tune controller reaches the HedgedBackend for its
    live hedge-delay actuation without the workload threading it."""
    b = backend
    seen = 0
    while b is not None and seen < 16:
        seen += 1
        if isinstance(b, cls):
            return b
        b = getattr(b, "inner", None)
    return None


def collect_tail_stats(backend) -> dict:
    """Walk the wrapper chain (``.inner`` links) and gather every tail
    layer's counters — the ``extra["tail"]`` stamp the read workload and
    the chaos scorecard consume."""
    out: dict = {}
    b = backend
    seen = 0
    while b is not None and seen < 16:
        seen += 1
        if isinstance(b, HedgedBackend):
            h = dict(b.stats)
            out["hedge"] = h
            out.setdefault("watchdog", {"stalls": 0})
            out["watchdog"]["stalls"] += h.pop("stalls", 0)
        elif isinstance(b, WatchdogBackend):
            out.setdefault("watchdog", {"stalls": 0})
            out["watchdog"]["stalls"] += b.stalls
        elif isinstance(b, BreakerBackend):
            out["breaker"] = b.breaker.snapshot()
        b = getattr(b, "inner", None)
    return out
