"""Adaptive ingest autotuner: the online controller that turns every
previously-static performance knob (fan-out, readahead, hedge delay)
into a controlled variable with a measurement loop and guardrails."""

from tpubench.tune.controller import (  # noqa: F401
    ACTUATED,
    Knob,
    RecorderSampler,
    TuneController,
)
