"""Online AIMD/hill-climbing controller over live workload knobs.

The reference hard-codes its fan-out (``--worker 48``, ``main.go:36``)
and every tpubench knob since is likewise static — yet the optimal
operating point is host-dependent (BENCH_r05: the native executor's
48-wide fan-out loses to a single hot loop on a 1-core host). This
module is the measurement loop that *finds* the knee of the
goodput/p99 curve during the run, congestion-control style:

* the workload registers :class:`Knob` actuators — live setters for
  worker fan-out (elastic gate / executor admission cap), prefetcher
  depth/byte-budget/workers (:meth:`Prefetcher.reclamp`), and the hedge
  delay (:meth:`HedgedBackend.set_hedge_delay`) — nothing restarts;
* a :class:`RecorderSampler` reads windowed goodput and p99 latency
  incrementally off the run's own per-worker
  :class:`~tpubench.metrics.recorder.LatencyRecorder` arrays (the
  ``snapshot_tail_ns`` path the periodic exporter already uses) plus a
  cumulative byte counter;
* :class:`TuneController` probes ONE knob per decision window
  (multiplying knobs double/halve — the slow-start shape; additive
  knobs step by a quantum), accepts a probe only when goodput improves
  by ``epsilon`` AND p99 stays within ``p99_guard`` x the warmup
  baseline, reverts anything else, freezes a knob after
  ``freeze_after_reverts`` unproductive probes (oscillation damping),
  and declares convergence when every knob is frozen at once — after
  which it holds the operating point and stops perturbing (so the
  post-convergence tail is guardrail-clean by construction).

Every decision is appended to ``windows`` (the ``extra["tune"]`` stamp)
and, when a flight ring is supplied, journaled as a ``kind="tune"``
record carrying a ``tune`` note — ``tpubench report timeline`` counts
them alongside hedge/stall/breaker events.

Clock, sleep and rng are injectable; tests drive :meth:`step` directly
with a fake sampler and never spin a thread.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Optional, Sequence

from tpubench.config import TUNE_KNOBS, TuneConfig

# Knob name -> (config field path, CLI flag dest). The knob-drift guard
# in tests/test_tune.py walks this: every entry must resolve to a real
# dataclass field in tpubench.config AND to a flag in cli._add_common,
# so the controller and the config surface can't silently diverge.
ACTUATED = {
    "workers": {"config": ("workload", "workers"), "cli": "workers"},
    "readahead": {"config": ("pipeline", "readahead"), "cli": "readahead"},
    "readahead_bytes": {
        "config": ("pipeline", "readahead_bytes"),
        "cli": "readahead_bytes",
    },
    "prefetch_workers": {
        "config": ("pipeline", "prefetch_workers"),
        "cli": "prefetch_workers",
    },
    "hedge_delay_s": {
        "config": ("transport", "tail", "hedge_delay_s"),
        "cli": "hedge_delay",
    },
    "staging_depth": {
        "config": ("staging", "depth"),
        "cli": "staging_depth",
    },
    "peer_budget_bytes": {
        "config": ("coop", "peer_budget_bytes"),
        "cli": "peer_budget_bytes",
    },
    "coop": {
        "config": ("coop", "enabled"),
        "cli": "coop",
    },
}
assert tuple(sorted(ACTUATED)) == tuple(sorted(TUNE_KNOBS))


# Shared knob-range formulas: the offline sweep (tune_cmd.sweep_axes)
# and the online controllers (read.py / train_ingest.py) must explore
# the SAME ranges, or the sweep recommends cells the controller can't
# reach — one definition each, next to the knob registry they belong to.
def readahead_ceiling(readahead: int) -> int:
    return min(64, max(8, 4 * readahead))


def prefetch_workers_ceiling(workers: int) -> int:
    return min(8, max(4, 2 * workers))


def staging_depth_ceiling(depth: int, pool_slabs: int = 0) -> int:
    """In-flight staging-window ceiling: past ~8 pending transfers the
    tunnel is saturated and every extra slot only pins host memory.
    ``pool_slabs`` (when the slab pool is explicitly sized) caps the
    ceiling so neither the sweep ladder nor a live grow probe can drive
    depth past the pool budget validate_pipeline_config enforces."""
    hi = min(8, max(4, 2 * depth))
    if pool_slabs > 0:
        hi = max(1, min(hi, pool_slabs))
    return hi


def hedge_delay_knob(value: float, set_fn) -> "Knob":
    """The hedge-delay knob around the configured delay (x8 both ways,
    floored so a multiplying float knob can always move)."""
    return Knob(
        "hedge_delay_s", value, set_fn,
        lo=max(0.001, value / 8), hi=max(0.002, value * 8),
        mode="mul", integer=False,
    )


class Knob:
    """One live-actuated knob: bounds, a step policy and a setter.

    ``mode="mul"`` knobs probe by doubling/halving (``factor``) — the
    slow-start shape, right for window-like quantities (fan-out,
    readahead depth, byte budgets). ``mode="add"`` knobs step by
    ``step``. Values clamp to [lo, hi]; integer knobs round."""

    __slots__ = ("name", "lo", "hi", "set_fn", "mode", "step", "factor",
                 "integer", "value", "initial")

    def __init__(self, name: str, value, set_fn: Callable, *,
                 lo, hi, mode: str = "mul", step=1, factor: float = 2.0,
                 integer: bool = True):
        if name not in ACTUATED:
            raise ValueError(f"unknown tune knob {name!r}")
        self.name = name
        # Bounds EXPAND to include the configured starting point: the
        # controller's view must match the live operating point, or the
        # first revert would "restore" a clamped value the run never had
        # (e.g. readahead=100 against a derived hi of 64).
        self.lo = min(lo, value)
        self.hi = max(hi, value)
        self.set_fn = set_fn
        self.mode = mode
        self.step = step
        self.factor = factor
        self.integer = integer
        self.value = self._clamp(value)
        self.initial = self.value

    def _clamp(self, v):
        v = min(self.hi, max(self.lo, v))
        return int(round(v)) if self.integer else float(v)

    def candidate(self, direction: int):
        """The probe value one step in ``direction`` (+1/-1), or None
        when already pinned at that bound."""
        if self.mode == "mul":
            v = self.value * self.factor if direction > 0 else (
                self.value / self.factor
            )
            if self.integer:
                # A stuck integer halving (1/2 -> 1) must still move.
                v = self.value + 1 if (direction > 0 and round(v) == self.value) \
                    else v
        else:
            v = self.value + direction * self.step
        v = self._clamp(v)
        return None if v == self.value else v

    def actuate(self, v) -> None:
        self.value = self._clamp(v)
        self.set_fn(self.value)


class RecorderSampler:
    """Windowed goodput/p99 off live recorders + a cumulative bytes fn.

    Reads only the NEW latency samples each window via
    ``snapshot_tail_ns`` (O(new) per window, safe against the owning
    worker's concurrent appends) and diffs the byte counter — the same
    mid-run-safe discipline as the periodic metrics exporter."""

    def __init__(self, recorders: Sequence, bytes_fn: Callable[[], int],
                 clock: Callable[[], float] = time.monotonic):
        self._recorders = list(recorders)
        self._offsets = [0] * len(self._recorders)
        self._bytes_fn = bytes_fn
        self._clock = clock
        self._t_last = clock()
        self._bytes_last = int(bytes_fn())

    def add_recorder(self, rec) -> None:
        self._recorders.append(rec)
        self._offsets.append(0)

    def sample(self) -> dict:
        now = self._clock()
        seconds = max(1e-9, now - self._t_last)
        self._t_last = now
        total = int(self._bytes_fn())
        delta = max(0, total - self._bytes_last)
        self._bytes_last = total
        lats = []
        for i, rec in enumerate(self._recorders):
            arr, self._offsets[i] = rec.snapshot_tail_ns(self._offsets[i])
            if arr.size:
                lats.extend(arr.tolist())
        p99_ms = None
        if lats:
            lats.sort()
            p99_ms = lats[min(len(lats) - 1, int(0.99 * len(lats)))] / 1e6
        return {
            "seconds": seconds,
            "goodput_bps": delta / seconds,
            "p99_ms": p99_ms,
            "reads": len(lats),
        }


class TuneController:
    """The per-run decision loop (module docstring). Construct with the
    workload's knobs + sampler; either call :meth:`step` once per window
    (tests) or :meth:`start`/:meth:`stop` the built-in thread."""

    def __init__(
        self,
        cfg: TuneConfig,
        knobs: Sequence[Knob],
        sampler,
        *,
        clock: Callable[[], float] = time.monotonic,
        rng: Optional[random.Random] = None,
        flight_ring=None,
    ):
        self.cfg = cfg
        self.knobs = list(knobs)
        self.sampler = sampler
        self._clock = clock
        self._rng = rng or random.Random(cfg.seed)
        self._flight = flight_ring
        self.windows: list[dict] = []
        self._baseline_p99: Optional[float] = None
        self._warmup_p99: list[float] = []
        self._stable_goodput = 0.0
        self._baseline_goodput = 0.0
        self.best_goodput = 0.0
        # Probe in flight: (knob, previous value) — judged by the NEXT
        # window, which measured the probed value.
        self._pending: Optional[tuple[Knob, object]] = None
        self._ki = 0  # round-robin cursor
        self._dir = {k.name: +1 if self._rng.random() < 0.75 else -1
                     for k in self.knobs}
        self._reverts = {k.name: 0 for k in self.knobs}
        self._frozen_until = {k.name: -1 for k in self.knobs}
        self.converged_at: Optional[int] = None
        self.accepts = 0
        self.reverts = 0
        self.guard_violations = 0
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self.error: Optional[str] = None

    # ------------------------------------------------------------ policy --
    def _judge(self, s: dict) -> str:
        """Accept or revert the pending probe against its window."""
        knob, prev = self._pending
        self._pending = None
        p99 = s["p99_ms"]
        guard_ok = (
            p99 is None or self._baseline_p99 is None
            or p99 <= self.cfg.p99_guard * self._baseline_p99
        )
        if not guard_ok:
            self.guard_violations += 1
            knob.actuate(prev)
            self._after_revert(knob)
            # Flip like a plain revert: re-probing the SAME over-guard
            # value would inject a second avoidable p99 violation into
            # the live run before the knob ever tries the other side.
            self._dir[knob.name] = -self._dir[knob.name]
            return "revert_guard"
        # Accept needs a STRICTLY positive window: with a zero-goodput
        # baseline (window shorter than a step), 0 >= 0*(1+eps) would
        # accept every probe — including harmful ones — forever.
        if s["goodput_bps"] > 0 and s["goodput_bps"] >= (
            self._stable_goodput * (1.0 + self.cfg.epsilon)
        ):
            self._stable_goodput = s["goodput_bps"]
            self._reverts[knob.name] = 0
            self.accepts += 1
            return "accept"
        knob.actuate(prev)
        self._after_revert(knob)
        self._dir[knob.name] = -self._dir[knob.name]
        return "revert"

    def _after_revert(self, knob: Knob) -> None:
        self.reverts += 1
        self._reverts[knob.name] += 1
        if self._reverts[knob.name] >= self.cfg.freeze_after_reverts:
            # Freeze for cooldown_windows FUTURE windows. This runs
            # inside _judge, BEFORE the current window's record is
            # appended, while the probe/convergence checks compare
            # against the post-append length (= the upcoming window's
            # index) — hence the +1, or cooldown_windows=1 would be a
            # no-op and convergence unreachable.
            self._frozen_until[knob.name] = (
                len(self.windows) + self.cfg.cooldown_windows + 1
            )
            self._reverts[knob.name] = 0

    def _next_probe(self) -> Optional[Knob]:
        w = len(self.windows)
        for _ in range(len(self.knobs)):
            knob = self.knobs[self._ki % len(self.knobs)]
            self._ki += 1
            if self._frozen_until[knob.name] > w:
                continue
            if knob.lo == knob.hi:
                continue  # inert
            return knob
        return None

    def _launch(self, knob: Knob) -> Optional[dict]:
        cand = knob.candidate(self._dir[knob.name])
        if cand is None:  # pinned at this bound: try the other side
            self._dir[knob.name] = -self._dir[knob.name]
            cand = knob.candidate(self._dir[knob.name])
        if cand is None:
            # Immovable from here in EITHER direction (e.g. a mul knob
            # whose configured start is 0): retire it permanently, or
            # it would block convergence forever without ever probing.
            self._frozen_until[knob.name] = 1 << 62
            return None
        prev = knob.value
        knob.actuate(cand)
        self._pending = (knob, prev)
        return {"knob": knob.name, "from": prev, "to": cand}

    # -------------------------------------------------------------- step --
    def step(self) -> dict:
        """One decision window: sample it, judge the pending probe,
        launch the next one. Returns the window record."""
        s = self.sampler.sample()
        w = len(self.windows)
        rec = {
            "window": w,
            "seconds": round(s["seconds"], 6),
            "goodput_bps": round(s["goodput_bps"], 1),
            "p99_ms": round(s["p99_ms"], 4) if s["p99_ms"] is not None else None,
            "reads": s["reads"],
            "values": {k.name: k.value for k in self.knobs},
            "objective": round(s["goodput_bps"], 1),
        }
        if w < self.cfg.warmup_windows:
            rec["verdict"] = "warmup"
            if s["p99_ms"] is not None:
                self._warmup_p99.append(s["p99_ms"])
                self._baseline_p99 = max(self._warmup_p99)
            self._stable_goodput = max(self._stable_goodput, s["goodput_bps"])
            self._baseline_goodput = self._stable_goodput
        elif self._pending is not None:
            rec["knob"] = self._pending[0].name
            rec["verdict"] = self._judge(s)
        else:
            rec["verdict"] = "hold"
            # Track environment drift at the stable point so a slow
            # window can't permanently inflate the accept bar.
            if s["goodput_bps"] > 0:
                self._stable_goodput = (
                    0.5 * self._stable_goodput + 0.5 * s["goodput_bps"]
                )
        self.best_goodput = max(self.best_goodput, s["goodput_bps"])
        self.windows.append(rec)
        w = len(self.windows)
        if self.converged_at is None and w > self.cfg.warmup_windows:
            if all(self._frozen_until[k.name] > w for k in self.knobs
                   if k.lo != k.hi) and any(k.lo != k.hi for k in self.knobs):
                self.converged_at = w
                rec["converged"] = True
        # Probe only while not converged: a settled session holds its
        # operating point (the post-convergence guardrail guarantee).
        if self.converged_at is None and w >= self.cfg.warmup_windows:
            probe = self._next_probe()
            if probe is not None:
                launched = self._launch(probe)
                if launched is not None:
                    rec["probe"] = launched
        self._note(rec)
        return rec

    def _note(self, rec: dict) -> None:
        if self._flight is None:
            return
        op = self._flight.begin(
            f"tune/w{rec['window']}", "", install=False, kind="tune"
        )
        op.note(
            "tune",
            window=rec["window"],
            verdict=rec["verdict"],
            knob=rec.get("knob") or (rec.get("probe") or {}).get("knob"),
            goodput_bps=rec["goodput_bps"],
            p99_ms=rec["p99_ms"],
            values=dict(rec["values"]),
        )
        op.finish(0)

    # ------------------------------------------------------------ thread --
    def start(self) -> None:
        """Spin the decision loop on its own daemon thread, one step per
        ``window_s`` (real runs; tests call step() directly)."""

        def loop() -> None:
            while not self._stop_evt.wait(self.cfg.window_s):
                try:
                    self.step()
                except Exception as exc:  # noqa: BLE001 — advisory layer
                    # Tuning must never kill a run: record and stop.
                    self.error = f"{type(exc).__name__}: {exc}"
                    return

        self._thread = threading.Thread(
            target=loop, name="tune-controller", daemon=True
        )
        self._thread.start()

    def stop(self) -> dict:
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        return self.stats()

    # ------------------------------------------------------------- stats --
    def stats(self) -> dict:
        post = (
            self.windows[self.converged_at:]
            if self.converged_at is not None else []
        )
        post_good = [w["goodput_bps"] for w in post]
        post_p99 = [w["p99_ms"] for w in post if w["p99_ms"] is not None]
        return {
            "enabled": True,
            "n_windows": len(self.windows),
            "windows": self.windows,
            "converged": self.converged_at is not None,
            "windows_to_converge": self.converged_at,
            "initial": {k.name: k.initial for k in self.knobs},
            "final": {k.name: k.value for k in self.knobs},
            "baseline": {
                "goodput_bps": round(self._baseline_goodput, 1),
                "p99_ms": self._baseline_p99,
            },
            "best_goodput_bps": round(self.best_goodput, 1),
            "converged_goodput_bps": (
                round(sum(post_good) / len(post_good), 1) if post_good else None
            ),
            "converged_p99_ms": max(post_p99) if post_p99 else None,
            "accepts": self.accepts,
            "reverts": self.reverts,
            "guard_violations": self.guard_violations,
            "guard": {
                "p99_guard": self.cfg.p99_guard,
                "baseline_p99_ms": self._baseline_p99,
            },
            "error": self.error,
        }
