"""Workload drivers (L4 of SURVEY §1): one module per reference binary.

* ``read``        — root GCS read bench (``main.go``), the flagship.
* ``train_ingest``— step-paced training-loop ingest over the pipeline
                    subsystem (chunk cache + readahead prefetch) with
                    data-stall accounting; no reference analog.
* ``read_fs``     — sequential FS read (``benchmark-script/read_operation``).
* ``write``       — durable write (``benchmark-script/write_operations``).
* ``listing``     — list bench (``benchmark-script/list_operation``).
* ``open_file``   — FD-hold bench (``benchmark-script/open_file``).
* ``ssd_compare`` — block-latency percentile bench (``benchmark-script/ssd_test``).
"""

from tpubench.workloads.common import WorkerGroup, WorkerError  # noqa: F401
from tpubench.workloads.read import ReadWorkload, run_read  # noqa: F401
