"""Shared arrival processes + popularity laws for open-loop workloads.

Every closed-loop tpubench workload paces itself (a fixed worker pool
pulls as fast as it can); the serve plane is OPEN-LOOP — requests arrive
on their own schedule whether or not the system keeps up, which is the
only regime where a saturation knee exists to measure (the Pulsar
enterprise-scale methodology: sweep offered load, report
latency-vs-load, not one operating point).

This module is the single definition of the two statistical surfaces
serve and the coop simulation must agree on:

* :func:`zipf_plan` — the Zipf-hot chunk popularity law (promoted out of
  ``pipeline/coop.py``, which imports it back, so the two workloads can
  never drift on what "hot set" means);
* the arrival processes — Poisson, bursty (two-state MMPP), diurnal
  (thinned nonhomogeneous Poisson) and replayed-trace — all returning a
  sorted timeline of arrival timestamps in *virtual seconds from run
  start*, deterministic for a given seed (``np.random.Philox``, the
  zipf_plan discipline).

Timelines are VIRTUAL: generation never sleeps. The dispatcher that
replays one applies :func:`scaled_gaps` — the shared
``TPUBENCH_BENCH_SLEEP_SCALE`` contract (``config.parse_sleep_scale``)
with a per-gap floor, so a scaled-down hermetic run still *paces* its
bursts instead of collapsing every gap to zero and measuring a batch
submit instead of an arrival process.
"""

from __future__ import annotations

import json
from typing import Optional, Sequence

import numpy as np

from tpubench.pipeline.cache import ChunkKey
from tpubench.storage.base import ObjectMeta


def _rng(seed: int) -> np.random.Generator:
    return np.random.Generator(np.random.Philox(seed))


# -------------------------------------------------------------- popularity --


def zipf_keys_weights(
    objects: Sequence[ObjectMeta],
    chunk_bytes: int,
    *,
    bucket: str = "",
    alpha: float = 1.2,
) -> tuple[list[ChunkKey], np.ndarray]:
    """The ranked chunk list + normalized Zipf(alpha) weight vector —
    shared setup for :func:`zipf_plan` and callers that draw MANY
    per-tenant streams over one object set (the serve schedule builder:
    enumerating keys and renormalizing per tenant would be
    O(tenants × chunks) for identical data)."""
    keys: list[ChunkKey] = []
    for meta in objects:
        off = 0
        while off < meta.size:
            n = min(chunk_bytes, meta.size - off)
            keys.append(ChunkKey(bucket, meta.name, meta.generation, off, n))
            off += n
    if not keys:
        raise ValueError("zipf_plan: empty object set")
    weights = 1.0 / np.power(
        np.arange(1, len(keys) + 1, dtype=np.float64), alpha
    )
    weights /= weights.sum()
    return keys, weights


def zipf_plan(
    objects: Sequence[ObjectMeta],
    chunk_bytes: int,
    n_accesses: int,
    *,
    bucket: str = "",
    alpha: float = 1.2,
    seed: int = 0,
) -> list[ChunkKey]:
    """A Zipf-hot chunk access sequence: chunks ranked across the object
    set, rank r drawn with probability ∝ 1/r^alpha — the hot-set shape
    real dataset popularity follows (and the one cooperative caching
    exists to exploit: most accesses land on a small shared hot set)."""
    keys, weights = zipf_keys_weights(
        objects, chunk_bytes, bucket=bucket, alpha=alpha
    )
    rng = _rng(seed)
    idx = rng.choice(len(keys), size=n_accesses, p=weights)
    return [keys[i] for i in idx]


# ---------------------------------------------------------------- arrivals --


def poisson_arrivals(
    rate_rps: float, duration_s: float, *, seed: int = 0,
    rng: Optional[np.random.Generator] = None,
) -> list[float]:
    """Homogeneous Poisson process: i.i.d. exponential inter-arrival
    gaps at ``rate_rps`` — the memoryless open-loop baseline."""
    if rate_rps <= 0 or duration_s <= 0:
        return []
    rng = rng if rng is not None else _rng(seed)
    out: list[float] = []
    t = 0.0
    # Draw in batches: one exponential at a time would make the rng call
    # count (and thus the stream position) depend on float rounding.
    est = max(16, int(rate_rps * duration_s * 1.5) + 8)
    while t < duration_s:
        for g in rng.exponential(1.0 / rate_rps, size=est):
            t += float(g)
            if t >= duration_s:
                break
            out.append(t)
    return out


def mmpp_arrivals(
    rate_rps: float, duration_s: float, *, burst_factor: float = 4.0,
    burst_fraction: float = 0.25, cycle_s: float = 1.0, seed: int = 0,
    rng: Optional[np.random.Generator] = None,
) -> list[float]:
    """Bursty arrivals: a two-state Markov-modulated Poisson process.
    The process alternates a quiet state and a burst state (the burst
    occupies ``burst_fraction`` of each ``cycle_s``); rates are scaled
    so the MEAN offered load stays ``rate_rps`` — the burst A/B varies
    shape, not volume. ``burst_factor`` is the burst-to-quiet rate
    ratio."""
    if rate_rps <= 0 or duration_s <= 0:
        return []
    rng = rng if rng is not None else _rng(seed)
    bf = max(1.0, burst_factor)
    frac = min(max(burst_fraction, 1e-6), 1.0 - 1e-6)
    # mean = quiet*(1-frac) + quiet*bf*frac  =>  quiet = mean / (1-frac+bf*frac)
    quiet = rate_rps / ((1.0 - frac) + bf * frac)
    burst = quiet * bf
    out: list[float] = []
    t = 0.0
    while t < duration_s:
        cycle_t = t % cycle_s
        in_burst = cycle_t < frac * cycle_s
        rate = burst if in_burst else quiet
        g = float(rng.exponential(1.0 / rate))
        # Clip the gap at the state boundary so a long quiet draw can't
        # leap over the next burst window (state changes mid-gap).
        boundary = (frac * cycle_s - cycle_t) if in_burst \
            else (cycle_s - cycle_t)
        if g > boundary:
            t += boundary
            continue
        t += g
        if t < duration_s:
            out.append(t)
    return out


def diurnal_arrivals(
    rate_rps: float, duration_s: float, *, period_s: float = 4.0,
    depth: float = 0.8, seed: int = 0,
    rng: Optional[np.random.Generator] = None,
) -> list[float]:
    """Diurnal arrivals: a nonhomogeneous Poisson process whose rate
    follows ``rate*(1 + depth*sin(2πt/period))`` — the day/night swing
    compressed to ``period_s``. Generated by thinning against the peak
    rate (the standard construction, deterministic under the seed)."""
    if rate_rps <= 0 or duration_s <= 0:
        return []
    rng = rng if rng is not None else _rng(seed)
    depth = min(max(depth, 0.0), 0.999)
    peak = rate_rps * (1.0 + depth)
    out: list[float] = []
    t = 0.0
    while True:
        t += float(rng.exponential(1.0 / peak))
        if t >= duration_s:
            break
        lam = rate_rps * (1.0 + depth * np.sin(2.0 * np.pi * t / period_s))
        if rng.random() < lam / peak:
            out.append(t)
    return out


def trace_arrivals(
    times: Sequence[float], duration_s: float = 0.0,
) -> list[float]:
    """Replayed-trace arrivals: explicit timestamps (seconds from run
    start), sorted, non-negative, clipped to ``duration_s`` when one is
    given — the bring-your-own-workload path."""
    out = sorted(float(t) for t in times if t >= 0)
    if duration_s > 0:
        out = [t for t in out if t < duration_s]
    return out


def load_trace(path: str) -> list[float]:
    """A trace file is a JSON list of arrival timestamps (seconds)."""
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, list):
        raise SystemExit(
            f"serve trace {path!r}: expected a JSON list of arrival "
            "timestamps (seconds from run start)"
        )
    return [float(t) for t in doc]


ARRIVAL_KINDS = ("poisson", "bursty", "diurnal", "trace")


def make_arrivals(
    kind: str, rate_rps: float, duration_s: float, *, seed: int = 0,
    burst_factor: float = 4.0, burst_fraction: float = 0.25,
    burst_cycle_s: float = 1.0, diurnal_period_s: float = 4.0,
    trace: Optional[Sequence[float]] = None,
) -> list[float]:
    """Dispatcher over the arrival kinds (one seed → one timeline; the
    schedule-replay test pins identical seeds → identical timelines)."""
    if kind == "poisson":
        return poisson_arrivals(rate_rps, duration_s, seed=seed)
    if kind == "bursty":
        return mmpp_arrivals(
            rate_rps, duration_s, burst_factor=burst_factor,
            burst_fraction=burst_fraction, cycle_s=burst_cycle_s, seed=seed,
        )
    if kind == "diurnal":
        return diurnal_arrivals(
            rate_rps, duration_s, period_s=diurnal_period_s, seed=seed,
        )
    if kind == "trace":
        return trace_arrivals(trace or (), duration_s)
    raise ValueError(
        f"unknown arrival kind {kind!r}; have {'/'.join(ARRIVAL_KINDS)}"
    )


def scaled_gaps(
    times: Sequence[float], scale: float, floor_s: float = 1e-4,
) -> list[float]:
    """Inter-arrival sleep gaps for replaying a virtual timeline under
    ``TPUBENCH_BENCH_SLEEP_SCALE`` (the shared ``parse_sleep_scale``
    contract): each positive gap scales by ``scale`` but never below
    ``floor_s`` — a scaled-to-zero schedule would submit the whole run
    as one batch and a "burst" would stop being a burst. ``scale == 0``
    keeps the floor for the same reason (0 disables *refill* sleeps
    elsewhere; an arrival process with no gaps is not that process)."""
    gaps: list[float] = []
    prev = 0.0
    for t in times:
        g = max(0.0, t - prev)
        prev = t
        gaps.append(max(g * scale, floor_s) if g > 0 else 0.0)
    return gaps
