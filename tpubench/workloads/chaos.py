"""``tpubench chaos`` — scripted fault timelines + the resilience scorecard.

Runs a workload (read or pod-ingest) against a hermetic target while a
time-phased :class:`~tpubench.config.FaultConfig` schedule turns faults
on and off mid-run, then scores how ingest *degraded and recovered*:

* **goodput retention** — goodput during the fault window as a fraction
  of the pre-fault baseline;
* **p99 inflation** — read p99 during the fault vs the baseline;
* **hedge win rate / wasted bytes, stall count, breaker open time** —
  what the tail-tolerance layer (storage/tail.py) actually did;
* **time-to-recover** — how long after the fault clears until windowed
  goodput is back to ≥90 % of baseline.

The per-read raw material is the PR-1 flight recorder: every read is a
phase-stamped record (with hedge/stall/breaker events as notes), so the
scorecard is computed offline from the run's own flight journal — and
``tpubench report timeline`` attributes the same events per read.

Hermetic by construction: the fault plane only exists in the fake
backend and the fake servers, so chaos supports ``--protocol fake``
(in-process store), ``http`` (in-process HTTP/1.1 server), ``http``
+ ``--http2`` (in-process h2 server, native client) and ``grpc``
(in-process gRPC wire server, dependency-free wire client). Wall-clock is
bounded: every phase window and time-shaped fault duration scales by
``TPUBENCH_BENCH_SLEEP_SCALE`` so CI can run a miniature timeline.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import tempfile
import time
from typing import Optional

from tpubench.config import BenchConfig, parse_sleep_scale, validate_fault_config

# Fault fields that are durations (seconds): these scale with the
# timeline so a scaled-down run keeps the same *shape*.
_TIME_FIELDS = ("latency_s", "per_read_latency_s", "stall_s")


def _sleep_scale() -> float:
    """Validated ``TPUBENCH_BENCH_SLEEP_SCALE`` — the SAME parser bench.py
    uses (tpubench.config), applied here to every phase window and
    time-shaped fault duration; unset = 1."""
    return parse_sleep_scale("chaos timeline durations")


def scaled_fault_dict(fdict: dict, scale: float) -> dict:
    """A fault-config dict with every phase window and time-shaped fault
    duration scaled by ``scale`` — the ONE definition of "run this
    timeline under TPUBENCH_BENCH_SLEEP_SCALE", shared by chaos and the
    replay driver (a replayed incident must scale exactly the way the
    incident run did, or the timeline's shape drifts between them).
    Returns a new dict; never mutates the input (the caller's config —
    and a replay's bundle — must survive a second run unscaled)."""
    out = dict(fdict)
    phases = []
    for t0, t1, plan in out.get("phases") or ():
        p = dict(plan)
        for f in _TIME_FIELDS:
            if p.get(f):
                p[f] = p[f] * scale
        phases.append([float(t0) * scale, float(t1) * scale, p])
    out["phases"] = phases
    for f in _TIME_FIELDS:
        if out.get(f):
            out[f] = out[f] * scale
    return out


# ------------------------------------------------------------ scorecard ---


def _segment_stats(reads: list, lo: float, hi: float,
                   duration: float) -> dict:
    """One timeline segment over ``reads`` = [(t_start, t_end, dur_ms,
    bytes), ...], bucketed by COMPLETION time: goodput is bytes that
    actually arrived during the segment's wall window, and a read that
    began just before the fault and crawled through it carries its
    latency into the segment where it finally landed."""
    durs = sorted(r[2] for r in reads if lo <= r[1] < hi)
    total = sum(r[3] for r in reads if lo <= r[1] < hi)

    def pct(p: float) -> float:
        if not durs:
            return 0.0
        return durs[min(len(durs) - 1, int(p * len(durs)))]

    return {
        "reads": len(durs),
        "bytes": total,
        "seconds": round(duration, 6),
        "goodput_gbps": (total / 1e9 / duration) if duration > 0 else 0.0,
        "p50_ms": pct(0.50),
        "p99_ms": pct(0.99),
    }


def resilience_scorecard(
    records: list[dict],
    phases: list,
    epoch_ns: int,
    tail_stats: Optional[dict] = None,
    recover_frac: float = 0.9,
) -> dict:
    """Score a run's flight records against its fault timeline.

    ``phases`` are the (scaled) ``[t0, t1, plan]`` windows; the fault
    window scored is their bounding box. ``epoch_ns`` is the
    ``perf_counter_ns`` stamp taken when the schedule was armed, mapping
    record timestamps onto timeline seconds."""
    fault_t0 = min(p[0] for p in phases)
    fault_t1 = max(p[1] for p in phases)
    reads = []  # (t_start_s, t_end_s, dur_ms, bytes), timeline-relative
    failed = 0
    for r in records:
        if r.get("kind", "read") != "read":
            continue
        if r.get("error"):
            failed += 1
            continue
        ph = r.get("phases", {})
        end_ns = ph.get("body_complete") or max(ph.values())
        start_ns = ph.get("enqueue", end_ns)
        reads.append((
            (start_ns - epoch_ns) / 1e9,
            (end_ns - epoch_ns) / 1e9,
            (end_ns - start_ns) / 1e6,
            int(r.get("bytes", 0)),
        ))
    run_end = max((r[1] for r in reads), default=fault_t1)

    inf = float("inf")
    base_s = _segment_stats(reads, -inf, fault_t0, fault_t0)
    fault_s = _segment_stats(reads, fault_t0, fault_t1, fault_t1 - fault_t0)
    rec_s = _segment_stats(reads, fault_t1, inf,
                           max(0.0, run_end - fault_t1))
    recovery = [r for r in reads if r[1] >= fault_t1]  # by completion

    retention = None
    if base_s["goodput_gbps"] > 0:
        retention = fault_s["goodput_gbps"] / base_s["goodput_gbps"]
    p99_inflation = None
    if base_s["p99_ms"] > 0:
        p99_inflation = fault_s["p99_ms"] / base_s["p99_ms"]

    # Time-to-recover: the first sliding window after the fault clears
    # whose goodput is back to >= recover_frac of baseline. A run that
    # bounces back instantly scores 0.0; None = not recovered (or no
    # baseline to recover to) within the run.
    ttr = None
    base_rate = base_s["goodput_gbps"] * 1e9  # B/s
    if base_rate > 0 and recovery:
        tail_len = max(1e-9, run_end - fault_t1)
        w = min(max(0.05, tail_len / 4), max(0.05, fault_t0))
        step = w / 4
        s = fault_t1
        while s + w <= run_end + step:
            got = sum(r[3] for r in recovery if s <= r[1] < s + w)
            if got / w >= recover_frac * base_rate:
                ttr = s - fault_t1
                break
            s += step

    card: dict = {
        "fault_window_s": [fault_t0, fault_t1],
        "baseline": base_s,
        "fault": fault_s,
        "recovery": rec_s,
        "goodput_retention": retention,
        "p99_inflation": p99_inflation,
        "time_to_recover_s": ttr,
        "recover_frac": recover_frac,
        "failed_reads": failed,
        "run_end_s": run_end,
        # A timeline the run never reached is a mis-sized experiment —
        # flag it rather than report a vacuous recovery. Zero successful
        # reads is the degenerate case of exactly that.
        "timeline_covered": bool(reads) and run_end >= fault_t1,
    }
    tail_stats = tail_stats or {}
    hedge = dict(tail_stats.get("hedge", {}))
    if hedge:
        launched = hedge.get("hedges", 0)
        hedge["win_rate"] = (
            hedge.get("hedge_wins", 0) / launched if launched else None
        )
    card["hedge"] = hedge
    card["stalls"] = tail_stats.get("watchdog", {}).get("stalls", 0)
    breaker = tail_stats.get("breaker")
    if breaker:
        card["breaker"] = {
            "opens": breaker.get("opens", 0),
            "open_s": breaker.get("open_s", 0.0),
            "state": breaker.get("state"),
        }
    return card


def format_scorecard(chaos: dict) -> str:
    """Human rendering of ``extra["chaos"]`` (also used by ``tpubench
    report`` on chaos result files)."""
    sc = chaos.get("scorecard", {})
    t0, t1 = sc.get("fault_window_s", (0, 0))
    lines = [
        f"== resilience scorecard ({chaos.get('workload', 'read')}; "
        f"fault window {t0:.2f}s-{t1:.2f}s) ==",
    ]
    for seg in ("baseline", "fault", "recovery"):
        s = sc.get(seg, {})
        lines.append(
            f"  {seg:<9} reads={s.get('reads', 0):<5} "
            f"goodput={s.get('goodput_gbps', 0.0):.4f} GB/s  "
            f"p50={s.get('p50_ms', 0.0):.2f} ms  "
            f"p99={s.get('p99_ms', 0.0):.2f} ms"
        )
    ret = sc.get("goodput_retention")
    infl = sc.get("p99_inflation")
    ttr = sc.get("time_to_recover_s")
    lines.append(
        "  goodput retention: "
        + (f"{ret:.1%}" if ret is not None else "n/a (no baseline)")
    )
    lines.append(
        "  p99 inflation:     "
        + (f"{infl:.2f}x" if infl is not None else "n/a")
    )
    lines.append(
        "  time-to-recover:   "
        + (f"{ttr:.3f}s" if ttr is not None else
           "not recovered within run"
           if sc.get("timeline_covered") else "n/a (run ended mid-fault)")
    )
    hedge = sc.get("hedge") or {}
    if hedge:
        wr = hedge.get("win_rate")
        wr_cell = f"{wr:.1%}" if wr is not None else "n/a"
        lines.append(
            f"  hedges: launched={hedge.get('hedges', 0)} "
            f"wins={hedge.get('hedge_wins', 0)} "
            f"losses={hedge.get('hedge_losses', 0)} "
            f"win_rate={wr_cell} "
            f"wasted_bytes={hedge.get('wasted_bytes', 0)}"
        )
    lines.append(f"  stalls detected:   {sc.get('stalls', 0)}")
    br = sc.get("breaker")
    if br:
        lines.append(
            f"  breaker: opens={br['opens']} open_s={br['open_s']:.3f} "
            f"state={br['state']}"
        )
    lines.append(f"  failed reads:      {sc.get('failed_reads', 0)}")
    return "\n".join(lines)


# -------------------------------------------------------------- workload --


def spawn_hermetic_server(cfg: BenchConfig, fault_plan=None, store=None):
    """In-process fake server speaking the real wire protocol (h1.1, the
    h2 server under ``transport.http2``, or the gRPC wire server under
    ``--protocol grpc``), backed by a prepopulated
    fake store carrying ``fault_plan`` — server-side injection, so
    stalls/resets/truncation happen ON THE WIRE. ``store`` overrides the
    default population (the replay driver rebuilds a bundle's recorded
    object set and serves THAT). Sets ``cfg.transport.endpoint`` (caller
    restores it) and pre-loads the C++ engine where the client path
    needs it, so first-use costs never land inside a measured window.
    One definition shared by ``tpubench chaos``, ``tpubench tune`` and
    ``tpubench replay`` — the hermetic-session surfaces must not drift.
    Returns the started server (caller stops it)."""
    from tpubench.storage.fake import FakeBackend

    w = cfg.workload
    if store is None:
        store = FakeBackend.prepopulated(
            prefix=w.object_name_prefix,
            count=max(w.workers, w.threads),
            size=w.object_size,
            fault=fault_plan,
        )
    if cfg.transport.protocol == "grpc":
        from tpubench.storage.fake_grpc_wire_server import FakeGrpcWireServer

        server = FakeGrpcWireServer(backend=store).start()
        # DirectPath can't apply to a loopback fake; forcing it off here
        # keeps the hermetic run warning-free (caller restores cfg).
        cfg.transport.directpath = False
    elif cfg.transport.http2:
        from tpubench.storage.fake_h2_server import FakeH2Server

        server = FakeH2Server(backend=store).start()
    else:
        from tpubench.storage.fake_server import FakeGcsServer

        server = FakeGcsServer(backend=store).start()
    cfg.transport.endpoint = server.endpoint
    if cfg.transport.http2 or cfg.transport.native_receive:
        from tpubench.native.engine import get_engine

        get_engine()
    return server


@contextlib.contextmanager
def hermetic_target(cfg: BenchConfig):
    """Hermetic-target guard for the lifecycle/drill CLI paths: under
    ``--protocol http``/``--protocol grpc`` with no endpoint, spawn the
    matching in-process fake server (carrying ``transport.fault`` when
    active, so ``tpubench ckpt-save --protocol grpc --fault-*`` injects
    ON THE WIRE) and restore the touched transport fields on exit.
    Yields ``None`` when the run already has a target (explicit endpoint,
    or a protocol like ``fake``/``local`` that needs no server)."""
    t = cfg.transport
    if t.protocol not in ("http", "grpc") or t.endpoint:
        yield None
        return
    from tpubench.storage.fake import FaultPlan

    plan = (
        FaultPlan(**dataclasses.asdict(t.fault)) if t.fault.active else None
    )
    restore = (t.endpoint, t.directpath)
    server = spawn_hermetic_server(cfg, fault_plan=plan)
    try:
        yield server
    finally:
        server.stop()
        t.endpoint, t.directpath = restore


def run_chaos(
    cfg: BenchConfig,
    timeline: Optional[list] = None,
    chaos_workload: str = "read",
    tracer=None,
):
    """Run ``chaos_workload`` under the scheduled fault timeline and
    return its RunResult with ``extra["chaos"]`` (the scorecard) stamped.

    ``timeline`` (``[[t0, t1, {fault fields}], ...]``) overrides
    ``cfg.transport.fault.phases``. The target is hermetic: the fake
    backend for ``--protocol fake``, an in-process fake GCS server for
    ``http`` (h1.1, or the h2 server with ``--http2``). ``tracer``
    (owned and flush-on-exit-closed by the CLI's ``tracer_session``)
    instruments the read workload's spans; train-ingest/pod-ingest
    trace through their flight ops alone."""
    fc = cfg.transport.fault
    if timeline is not None:
        fc.phases = timeline
    # Restored on exit (the caller may reuse cfg; a second run must not
    # inherit this run's stripped byte phases or its host events).
    restore_phases = list(fc.phases or ())
    restore_member_tl = list(cfg.serve.membership_timeline)
    # Host-level membership faults (kill_host / leave_host / pause_host
    # / rejoin_host) ride the SAME timeline as the byte-level fault
    # plan and compose with it — split them out before fault validation
    # (they are membership-plane events, not FaultPlan fields). They
    # are only meaningful under the elastic serve pod.
    from tpubench.config import MEMBER_TIMELINE_ACTIONS

    member_phases = []
    byte_phases = []
    for i, ph in enumerate(fc.phases or ()):
        if (isinstance(ph, (list, tuple)) and len(ph) == 3
                and isinstance(ph[2], dict)
                and set(ph[2]) & set(MEMBER_TIMELINE_ACTIONS)):
            # Numeric window check HERE: member phases skip the byte-
            # level validate_fault_config below, and the full timeline
            # validator only runs later inside run_serve — a malformed
            # stamp must still die as a one-line SystemExit, never a
            # TypeError in the scaling arithmetic.
            try:
                t0, t1 = float(ph[0]), float(ph[1])
            except (TypeError, ValueError):
                raise SystemExit(
                    f"chaos: timeline[{i}]: host-fault window "
                    f"[{ph[0]!r}, {ph[1]!r}] must be numeric"
                ) from None
            member_phases.append([t0, t1, dict(ph[2])])
        else:
            byte_phases.append(ph)
    fc.phases = byte_phases
    if member_phases and chaos_workload != "serve":
        raise SystemExit(
            "chaos: host-level faults (kill_host/leave_host/pause_host/"
            "rejoin_host) compose with the elastic serve pod only — use "
            "--chaos-workload serve with --serve-hosts >= 2"
        )
    validate_fault_config(fc, "transport.fault")
    if not fc.phases and not member_phases:
        raise SystemExit(
            "chaos: no fault timeline — pass --chaos-timeline or the "
            "--chaos-fault/--chaos-start/--chaos-duration trio "
            "(fault.phases in a config file also works)"
        )
    proto = cfg.transport.protocol
    if proto not in ("fake", "http", "grpc") or (
        proto in ("http", "grpc") and cfg.transport.endpoint
    ):
        raise SystemExit(
            "chaos: hermetic protocols only (fake, http[--http2] or "
            "grpc against the in-process fake servers), not "
            f"{proto!r} with endpoint {cfg.transport.endpoint!r} — the "
            "fault plane lives in the fake backend/servers"
        )

    # Scale into a LOCAL fault dict — never back into cfg, which the
    # caller may reuse (a second run must not double-scale its timeline).
    scale = _sleep_scale()
    fdict = scaled_fault_dict(dataclasses.asdict(fc), scale)
    phases = fdict["phases"]
    # The serve plane scales its own (virtual) clock, so the membership
    # timeline passes through UNSCALED; the resilience scorecard maps
    # real record stamps onto scaled seconds, so its fault-window
    # bounding box takes the SCALED twin of each member window.
    score_phases = phases + [
        [t0 * scale, t1 * scale, dict(spec)]
        for t0, t1, spec in member_phases
    ]
    if member_phases:
        cfg.serve.membership_timeline = member_phases

    # Flight recorder is the scorecard's raw material: force it on, sized
    # to hold every read, journaled to disk (a temp path unless the run
    # already asked for one). Every cfg field touched here is restored on
    # exit — the caller's config must survive a second run unchanged
    # (the hedged-vs-plain A/B reuses one config).
    w = cfg.workload
    cfg_restore = {
        "endpoint": cfg.transport.endpoint,
        "directpath": cfg.transport.directpath,
        "flight_records": cfg.obs.flight_records,
        "flight_journal": cfg.obs.flight_journal,
        "journal_max_bytes": cfg.obs.journal_max_bytes,
    }
    # The scorecard segments a COMPLETE journal by completion time:
    # size-bounded rotation could silently drop the baseline window's
    # records and skew goodput-retention toward the fault window, so
    # rotation is off for the scorecard's own journal (restored below;
    # the ring was just sized to hold every expected read anyway).
    cfg.obs.journal_max_bytes = 0
    reads_expected = w.read_calls_per_worker
    if chaos_workload == "train-ingest":
        pl = cfg.pipeline
        reads_expected = pl.steps * pl.epochs * pl.batch_shards
    elif chaos_workload == "serve":
        reads_expected = int(cfg.serve.rate_rps * cfg.serve.duration_s)
    cfg.obs.flight_records = max(
        cfg.obs.flight_records, reads_expected * 2 + 64
    )
    tmp_journal = None
    if not cfg.obs.flight_journal:
        fd, tmp_journal = tempfile.mkstemp(prefix="tpubench-chaos-", suffix=".json")
        os.close(fd)
        cfg.obs.flight_journal = tmp_journal

    from tpubench.storage.fake import FaultPlan

    server = None
    backend = None
    plan = FaultPlan(**fdict)
    try:
        if proto in ("http", "grpc"):
            server = spawn_hermetic_server(cfg, fault_plan=plan)

        # Pre-build everything expensive (workload import, client
        # backend), then arm: timeline second 0 ≈ the first read, so the
        # baseline window actually measures reads, not bring-up. Both
        # workloads get the SAME armed plan (via the explicit backend),
        # so phase windows and scorecard segments share one epoch.
        if chaos_workload == "read":
            from tpubench.workloads.read import run_read

            def _runner(cfg, backend):
                # The CLI's tracer_session hands the tracer in; spans
                # recorded during the fault window are the chaos run's
                # per-read causal story (report trace on the journal).
                return run_read(cfg, backend=backend, tracer=tracer)
        elif chaos_workload == "train-ingest":
            # The pipeline smoke path: fault schedules exercise the
            # prefetcher + cache; a blackhole window surfaces as
            # data-stall time in extra["pipeline"]["stall"] (and as
            # stall_begin/stall_end step phases in the journal), never
            # as a hang — demand reads ride the same tail-tolerance +
            # retry stack as every other workload.
            from tpubench.workloads.train_ingest import (
                run_train_ingest as _runner,
            )
        elif chaos_workload == "pod-ingest":
            from tpubench.workloads.pod_ingest import run_pod_ingest

            def _runner(cfg, backend):
                return run_pod_ingest(cfg, backend=backend)
        elif chaos_workload == "serve":
            # The open-loop (optionally elastic) serve plane: byte-level
            # faults hit the shared origin through the fault plan while
            # host-level member_phases change the pod's shape — the
            # "pod that changes shape under live faulty traffic" cell.
            from tpubench.workloads.serve import run_serve

            def _runner(cfg, backend):
                return run_serve(cfg, backend=backend, tracer=tracer)
        else:
            raise SystemExit(
                f"chaos: unknown workload {chaos_workload!r} "
                "(read|pod-ingest|train-ingest|serve)"
            )
        from tpubench.storage import open_backend

        backend = open_backend(cfg, fault=plan if proto == "fake" else None)
        # One best-effort warm-up read before arming: connection setup,
        # TLS, stat caches and thread machinery must not be billed to
        # the timeline's baseline window.
        try:
            from tpubench.storage.base import read_object_through

            read_object_through(
                backend.open_read(f"{w.object_name_prefix}0"),
                memoryview(bytearray(w.granule_bytes)),
            )
        except Exception:  # noqa: BLE001 — the run will surface it
            pass
        epoch_ns = time.perf_counter_ns()
        plan.arm()
        res = _runner(cfg, backend=backend)

        jpath = res.extra.get("flight_journal") or cfg.obs.flight_journal
        with open(jpath) as f:
            records = json.load(f).get("records", [])
        if tmp_journal is not None:
            # The journal was only the scorecard's scratch input — don't
            # advertise a path that is about to be deleted.
            res.extra.pop("flight_journal", None)
        # Tail-tolerance counters: the read workload stamps them itself;
        # pod-ingest doesn't, but run_chaos holds the wrapped backend —
        # collect here so the scorecard never under-reports what the
        # hedging/watchdog/breaker machinery actually did.
        if "tail" not in res.extra:
            from tpubench.storage.tail import collect_tail_stats

            ts = collect_tail_stats(backend)
            if ts:
                res.extra["tail"] = ts
        res.workload = "chaos"
        res.extra["chaos"] = {
            "workload": chaos_workload,
            "timeline": phases,
            "member_timeline": member_phases,
            "sleep_scale": scale,
            "scorecard": resilience_scorecard(
                records, score_phases, epoch_ns,
                tail_stats=res.extra.get("tail"),
            ),
        }
        return res
    finally:
        if backend is not None:
            backend.close()
        if server is not None:
            server.stop()
        if tmp_journal is not None:
            try:
                os.unlink(tmp_journal)
            except OSError:
                pass
        cfg.transport.endpoint = cfg_restore["endpoint"]
        cfg.transport.directpath = cfg_restore["directpath"]
        cfg.obs.flight_records = cfg_restore["flight_records"]
        cfg.obs.flight_journal = cfg_restore["flight_journal"]
        cfg.obs.journal_max_bytes = cfg_restore["journal_max_bytes"]
        cfg.transport.fault.phases = restore_phases
        cfg.serve.membership_timeline = restore_member_tl
