"""Checkpoint save/restore workloads (``tpubench ckpt-save`` /
``tpubench ckpt-restore``).

The storage-lifecycle pair (ROADMAP item: checkpoint restore/save):

* **ckpt-save** — the first WRITE path: a sharded-model manifest of
  ``lifecycle.objects`` shard-objects streamed out through resumable
  multi-part uploads (session → content-range parts → finalize), with
  part-level retry/resume riding the backend stack's resuming writer so
  breaker/retry compose under upload faults exactly like they do under
  read faults. Scorecard: save goodput, part p50/p99, resumed-part
  count, and ZERO corrupt finalizes (readback crc32 vs the manifest).
* **ckpt-restore** — the manifest read back into per-host shard ranges
  (dist.shard's lane-aligned decomposition) and staged into SHARDED
  device arrays across the mesh, with **time-to-restore** as the
  headline metric and byte-identity verified against the manifest crcs.
"""

from __future__ import annotations

import time
import zlib
from typing import Optional

from tpubench.config import BenchConfig
from tpubench.lifecycle import format_lifecycle_scorecard  # noqa: F401 (CLI re-export)
from tpubench.lifecycle.manifest import (
    CkptManifest,
    build_manifest,
    manifest_name,
    read_manifest,
    shard_content,
)
from tpubench.lifecycle.upload import readback_crc32, upload_object
from tpubench.metrics import LatencyRecorder, merge_recorders
from tpubench.metrics.percentiles import summarize_ns
from tpubench.metrics.report import RunResult
from tpubench.obs.flight import (
    flight_from_config,
    host_journal_path,
    transport_label,
)
from tpubench.storage import open_backend
from tpubench.workloads.common import WorkerGroup


def _flight_finish(cfg: BenchConfig, flight, res: RunResult,
                   workload: str) -> None:
    """Shared journal/summary stamping tail (read.py discipline)."""
    if flight is None:
        return
    res.extra["flight"] = flight.summary()
    if cfg.obs.flight_journal:
        d = cfg.dist
        jpath = host_journal_path(
            cfg.obs.flight_journal, d.process_id, d.num_processes
        )
        res.extra["flight_journal"] = flight.write_journal(
            jpath, extra={"workload": workload},
            max_bytes=cfg.obs.journal_max_bytes,
        )


def run_ckpt_save(
    cfg: BenchConfig, backend=None, manifest: Optional[CkptManifest] = None,
) -> RunResult:
    lc = cfg.lifecycle
    owns = backend is None
    backend = backend or open_backend(cfg)
    flight = flight_from_config(cfg)
    tlabel = transport_label(cfg)
    manifest = manifest or build_manifest(lc.prefix, lc.objects,
                                          lc.object_bytes)
    n_workers = min(lc.writers, len(manifest.objects))
    part_recs = [LatencyRecorder(f"part{i}") for i in range(n_workers)]
    obj_recs = [LatencyRecorder(f"obj{i}") for i in range(n_workers)]
    parts = [0] * n_workers
    resumed = [0] * n_workers
    uploaded = [0] * n_workers
    corrupt = [0] * n_workers

    def worker(i: int, cancel) -> None:
        ring = flight.worker(f"save{i}") if flight is not None else None
        for spec in manifest.objects[i::n_workers]:
            if cancel.is_set():
                break
            data = shard_content(spec.name, spec.size)
            t0 = time.perf_counter_ns()
            op = (
                ring.begin(spec.name, tlabel, enqueue_ns=t0, kind="upload")
                if ring is not None else None
            )
            try:
                _meta, stats = upload_object(
                    backend, spec.name, data.data, lc.part_bytes,
                    part_recorder=part_recs[i],
                )
            except BaseException as e:
                if op is not None:
                    op.finish(error=e)
                raise
            obj_recs[i].record_ns(time.perf_counter_ns() - t0)
            if op is not None:
                op.finish(stats["bytes"])
            parts[i] += stats["parts"]
            resumed[i] += stats["resumed_parts"]
            uploaded[i] += stats["bytes"]
            if lc.verify and readback_crc32(
                backend, spec.name, spec.size
            ) != spec.crc32:
                # A finalize that committed wrong bytes is the one
                # failure a resumable upload may NEVER have.
                corrupt[i] += 1

    t0 = time.perf_counter()
    try:
        import contextlib

        with (flight.activate() if flight is not None
              else contextlib.nullcontext()):
            gres = WorkerGroup(
                abort_on_error=cfg.workload.abort_on_error
            ).run(n_workers, worker, name="ckpt-save")
        # The manifest lands LAST (restore's readiness marker), through
        # the one-shot media path — both write surfaces exercised. It is
        # the READINESS marker: under abort_on_error=False a failed or
        # corrupt shard means the checkpoint is NOT restorable, so no
        # manifest may be published.
        if gres.error_count == 0 and sum(corrupt) == 0:
            backend.write(
                manifest_name(lc.prefix), manifest.to_json().encode()
            )
        wall = time.perf_counter() - t0
    finally:
        if owns:
            backend.close()
    total = sum(uploaded)
    part_all = merge_recorders(part_recs)
    res = RunResult(
        workload="ckpt_save",
        config=cfg.to_dict(),
        bytes_total=total,
        wall_seconds=wall,
        gbps=(total / 1e9) / wall if wall > 0 else 0.0,
        gbps_per_chip=(total / 1e9) / wall if wall > 0 else 0.0,
        summaries={
            "part": summarize_ns(part_all),
            "object_upload": summarize_ns(merge_recorders(obj_recs)),
        } if part_all.size else {},
        errors=gres.error_count + sum(corrupt),
    )
    res.extra["lifecycle"] = {
        "op": "save",
        "objects": len(manifest.objects),
        "bytes": total,
        "parts": sum(parts),
        "part_bytes": lc.part_bytes,
        "goodput_gbps": res.gbps,
        "part_latency": (
            summarize_ns(part_all).to_dict() if part_all.size else None
        ),
        "resumed_parts": sum(resumed),
        "corrupt_finalizes": sum(corrupt),
        "verified": bool(lc.verify) and sum(corrupt) == 0,
        "worker_errors": gres.error_count,
    }
    _flight_finish(cfg, flight, res, "ckpt_save")
    return res


def run_ckpt_restore(cfg: BenchConfig, backend=None) -> RunResult:
    lc = cfg.lifecycle
    lane = cfg.staging.lane
    owns = backend is None
    backend = backend or open_backend(cfg)
    flight = flight_from_config(cfg)
    tlabel = transport_label(cfg)
    try:
        manifest = read_manifest(backend, lc.prefix)
        use_device = lc.restore_device
        mesh = None
        n_shards = 1
        if use_device:
            try:
                from tpubench.dist.reassemble import make_mesh

                mesh = make_mesh(axis=cfg.dist.mesh_axis)
                n_shards = int(mesh.devices.size)
            except Exception as e:  # noqa: BLE001 — jax-free degrade
                import sys

                print(
                    f"ckpt-restore: device staging unavailable ({e}); "
                    "host-RAM restore", file=sys.stderr,
                )
                use_device = False
        from tpubench.dist.shard import ShardTable

        import numpy as np

        tables = [
            ShardTable.build(spec.size, n_shards, align=lane)
            for spec in manifest.objects
        ]
        def _prefaulted(nbytes: int):
            # Eager-touch the destination pages: np.zeros maps lazily,
            # and first-touch faults inside the timed fetch window would
            # bill host-memory setup to storage goodput.
            b = np.empty(nbytes, dtype=np.uint8)
            b.fill(0)
            return b

        buffers = [
            [_prefaulted(t.shard_bytes) for _ in range(n_shards)]
            for t in tables
        ]
        n_workers = min(lc.readers, len(manifest.objects) * n_shards)
        verify_fail = [0] * max(1, n_workers)

        # ---- fetch: every (object, shard) range, fanned over readers --
        work = [
            (oi, si)
            for oi in range(len(manifest.objects))
            for si in range(n_shards)
        ]

        def fetch(i: int, cancel) -> None:
            from tpubench.workloads.common import fetch_shard

            ring = flight.worker(f"restore{i}") if flight is not None else None
            for oi, si in work[i::n_workers]:
                if cancel.is_set():
                    break
                spec = manifest.objects[oi]
                op = (
                    ring.begin(spec.name, tlabel)
                    if ring is not None else None
                )
                try:
                    fetch_shard(
                        backend, spec.name, tables[oi], si, buffers[oi][si]
                    )
                except BaseException as e:
                    if op is not None:
                        op.finish(error=e)
                    raise
                if op is not None:
                    op.mark("body_complete")
                    op.finish(tables[oi].shard(si).length)

        import contextlib

        t0 = time.perf_counter()
        with (flight.activate() if flight is not None
              else contextlib.nullcontext()):
            gres = WorkerGroup(
                abort_on_error=cfg.workload.abort_on_error
            ).run(n_workers, fetch, name="ckpt-restore")
        t_fetch = time.perf_counter() - t0

        # ---- verify: byte identity against the manifest's crc32s ------
        verified = True
        if lc.verify:
            for oi, spec in enumerate(manifest.objects):
                crc = 0
                for si in range(n_shards):
                    sh = tables[oi].shard(si)
                    crc = zlib.crc32(
                        memoryview(buffers[oi][si])[:sh.length], crc
                    )
                if crc & 0xFFFFFFFF != spec.crc32:
                    verified = False
                    verify_fail[0] += 1

        # ---- stage: shard buffers → sharded device arrays --------------
        t0 = time.perf_counter()
        arrays = []
        if use_device:
            import jax

            from tpubench.dist.reassemble import shard_to_device_array

            for oi in range(len(manifest.objects)):
                arrays.append(shard_to_device_array(
                    buffers[oi], mesh, cfg.dist.mesh_axis, lane
                ))
            for a in arrays:
                jax.block_until_ready(a)
        t_stage = time.perf_counter() - t0
        time_to_restore = t_fetch + t_stage
    finally:
        if owns:
            backend.close()

    total = manifest.total_bytes
    res = RunResult(
        workload="ckpt_restore",
        config=cfg.to_dict(),
        bytes_total=total,
        wall_seconds=time_to_restore,
        gbps=(total / 1e9) / time_to_restore if time_to_restore > 0 else 0.0,
        gbps_per_chip=(
            (total / 1e9) / time_to_restore / max(1, n_shards)
            if time_to_restore > 0 else 0.0
        ),
        n_chips=max(1, n_shards) if use_device else 1,
        errors=gres.error_count + sum(verify_fail),
    )
    res.extra["lifecycle"] = {
        "op": "restore",
        "objects": len(manifest.objects),
        "bytes": total,
        "time_to_restore_s": time_to_restore,
        "fetch_seconds": t_fetch,
        "stage_seconds": t_stage,
        "goodput_gbps": res.gbps,
        "staged": use_device,
        "shards_per_object": n_shards,
        "verified": verified if lc.verify else None,
        "worker_errors": gres.error_count,
    }
    _flight_finish(cfg, flight, res, "ckpt_restore")
    return res
