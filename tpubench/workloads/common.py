"""Worker fan-out with errgroup semantics.

The reference's L3 is ``golang.org/x/sync/errgroup``: N goroutines, first
error cancels the run and propagates (``main.go:59,200-212``). Python
equivalent: a thread pool whose workers poll a shared cancel event; the first
exception is re-raised after join. I/O-bound workers release the GIL inside
socket/file syscalls, so threads are the right concurrency primitive here
(the native C++ engine additionally releases the GIL for the block-I/O hot
loops).

SURVEY §5.3's prescription — per-worker failure isolation instead of
pod-wide abort — is the ``abort_on_error=False`` mode: failed workers are
recorded as holes (error count + which shards) and the run completes.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Optional


class WorkerError(Exception):
    def __init__(self, worker_id: int, cause: BaseException):
        super().__init__(f"worker {worker_id} failed: {cause!r}")
        self.worker_id = worker_id
        self.cause = cause


@dataclass
class GroupResult:
    errors: list[WorkerError] = field(default_factory=list)

    @property
    def error_count(self) -> int:
        return len(self.errors)


class ElasticGate:
    """Live worker-fan-out gate for thread-pool workloads (the tune
    controller's Python-path workers actuation).

    All ``total`` threads are spawned up front; only the first
    ``active`` are admitted through :meth:`admit` — the rest PARK on the
    gate's condvar (not busy-waiting, not exiting) until the controller
    grows the pool back or the run ends. Shrinks take effect at each
    worker's next admit (its in-flight read completes normally — live
    resize, never a mid-read cancel)."""

    def __init__(self, active: int, total: int):
        self.total = max(1, total)
        self._active = max(1, min(active, self.total))
        self._cond = threading.Condition()

    @property
    def active(self) -> int:
        return self._active

    def set_active(self, n: int) -> None:
        with self._cond:
            self._active = max(1, min(int(n), self.total))
            self._cond.notify_all()

    def admit(self, worker_id: int, cancel: threading.Event) -> bool:
        """Block while ``worker_id`` is parked; True = proceed with the
        next unit of work, False = the run was cancelled while parked.
        The short wait timeout is only a safety net against a missed
        cancel-set (cancel has no condvar of its own)."""
        with self._cond:
            while worker_id >= self._active:
                if cancel.is_set():
                    return False
                self._cond.wait(0.05)
        return not cancel.is_set()


class WorkerGroup:
    """Run ``fn(worker_id, cancel_event)`` across N threads."""

    def __init__(self, abort_on_error: bool = True):
        self.abort_on_error = abort_on_error
        self.cancel = threading.Event()

    def run(
        self,
        n_workers: int,
        fn: Callable[[int, threading.Event], None],
        name: str = "worker",
    ) -> GroupResult:
        errors: list[Optional[WorkerError]] = [None] * n_workers

        def _wrap(i: int) -> None:
            try:
                fn(i, self.cancel)
            except BaseException as exc:  # noqa: BLE001 — recorded, maybe re-raised
                errors[i] = WorkerError(i, exc)
                if self.abort_on_error:
                    self.cancel.set()

        threads = [
            threading.Thread(target=_wrap, args=(i,), name=f"{name}-{i}", daemon=True)
            for i in range(n_workers)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

        collected = [e for e in errors if e is not None]
        if collected and self.abort_on_error:
            # errgroup returns the *first* error (main.go:212-219).
            raise collected[0]
        return GroupResult(errors=collected)


def fetch_shard(backend, name: str, table, shard_index: int, buffer) -> None:
    """Fetch one byte-range shard of ``name`` into ``buffer`` (host staging
    buffer of ``table.shard_bytes`` capacity), zeroing the padding tail.

    Shared by the pod-ingest workloads (one-shot and streamed) so the hot
    fetch path has a single definition. The explicit tail-zeroing matters
    when buffers are *reused* across objects of different sizes (the
    streamed pipeline's double-buffer sets): without it, bytes of a
    previously staged object would survive in the pad region and be
    gathered into the current object's pod array.
    """
    sh = table.shard(shard_index)
    buffer[sh.length :] = 0  # zero pad (and the whole buffer for an empty shard)
    if sh.length == 0:
        return
    mv = memoryview(buffer)
    reader = backend.open_read(name, start=sh.start, length=sh.length)
    got = 0
    try:
        while got < sh.length:
            r = reader.readinto(mv[got : sh.length])
            if r <= 0:
                break
            got += r
    finally:
        # Flight-recorder phase: the reader's own stamp (native CLOCK_
        # MONOTONIC or Python perf_counter — same clock on Linux) lands
        # on the calling worker's current op; no-op when none is active.
        fb = getattr(reader, "first_byte_ns", None)
        if fb:
            from tpubench.obs.flight import note_phase

            note_phase("first_byte", fb)
        reader.close()
    if got != sh.length:
        raise IOError(f"{name} shard {shard_index}: short fetch {got}/{sh.length}")


def zero_failed_shards(gres: GroupResult, table, buffers, local_idx) -> dict:
    """Turn fetch failures into deterministic HOLES (SURVEY §5.3): zero each
    failed worker's buffer (critical when buffers are reused across objects)
    and return the uniform hole record ``{"shards": [global indices],
    "bytes": missing}`` both pod-ingest workloads report."""
    for e in gres.errors:
        buffers[e.worker_id][:] = 0
    shards = sorted(local_idx[e.worker_id] for e in gres.errors)
    return {
        "shards": shards,
        "bytes": sum(table.shard(i).length for i in shards),
    }


def global_hole_totals(holes: dict) -> dict:
    """Pod-wide hole totals. Each process only sees failures of ITS local
    shard fetches; delivered-bytes accounting must subtract every host's
    holes or non-failing hosts report healthy bandwidth for a degraded
    gather. Single-process: identity. Multi-host: all-gather the per-process
    (shard_count, bytes) pair over DCN and sum."""
    import jax

    if jax.process_count() == 1:
        return {"shards": len(holes["shards"]), "bytes": holes["bytes"]}
    import numpy as np
    from jax.experimental import multihost_utils

    local = np.array([len(holes["shards"]), holes["bytes"]], dtype=np.int64)
    all_counts = np.asarray(multihost_utils.process_allgather(local))
    return {
        "shards": int(all_counts[:, 0].sum()),
        "bytes": int(all_counts[:, 1].sum()),
    }


def fetch_shards_mux(backend, cfg, name, table, local_idx, buffers):
    """Multiplexed shard fetch: all of this host's byte-range shards ride
    ONE connection as concurrent h2 streams instead of a thread per shard
    — no fan-out threads, one socket, per-stream failure isolation. Two
    backends support it: native gRPC (grpc-go's default multiplexing
    shape) and the whole-client http2 mode (ranged GETs multiplexed by
    the same h2 machinery). Failed ranges re-fetch under the configured
    gax policy (the same ``transport.retry`` the threaded path gets from
    RetryingBackend — bypassing the wrapper must not bypass the policy).
    Returns a GroupResult (raising the first error under
    ``abort_on_error``, WorkerGroup parity), or None when the
    backend/config doesn't support it — the caller falls back to the
    thread fan-out. Shared by pod-ingest and the streamed pipeline.
    """
    import time as _time

    try:
        # The gRPC backend needs the generated storage-v2 stubs; their
        # absence must not break the THREADED fetch path for every other
        # backend (this import is reachable from all pod workloads).
        from tpubench.storage.gcs_grpc import GcsGrpcBackend
    except ImportError:
        GcsGrpcBackend = None  # type: ignore[assignment,misc]
    from tpubench.storage.gcs_http import GcsHttpBackend
    from tpubench.storage.retry import Backoff, _is_retryable

    inner = getattr(backend, "inner", backend)
    supported = (
        GcsGrpcBackend is not None
        and isinstance(inner, GcsGrpcBackend)
        and inner.transport.native_receive
    ) or (isinstance(inner, GcsHttpBackend) and inner.transport.http2)
    if not (supported and len(local_idx) > 0):
        return None
    rngs = []
    for k, gi in enumerate(local_idx):
        sh = table.shard(gi)
        buffers[k][sh.length:] = 0  # pad tail (fetch_shard parity)
        rngs.append((sh.start, sh.length))

    rcfg = cfg.transport.retry
    start_t = _time.monotonic()
    final: list = [None] * len(rngs)
    remaining = list(range(len(rngs)))
    # Per-range attempt chains: a range failing for the FIRST time in
    # round N still gets the full gax allowance (max_attempts, its own
    # backoff progression) — one shared round counter would grant it
    # only the leftovers (RetryScheduler tracks per-tag chains and
    # RetryingBackend per-call; this batch path must match).
    attempts = [0] * len(rngs)
    backoffs = [Backoff(rcfg) for _ in rngs]
    while remaining:
        sub_errs = inner.read_ranges(
            name,
            [rngs[i] for i in remaining],
            [buffers[i] for i in remaining],
        )
        for j, e in enumerate(sub_errs):
            final[remaining[j]] = e
        retryable = []
        for j, e in enumerate(sub_errs):
            i = remaining[j]
            if e is None or not _is_retryable(e, rcfg.policy):
                continue
            attempts[i] += 1
            if rcfg.max_attempts and attempts[i] >= rcfg.max_attempts:
                continue
            retryable.append(i)
        if not retryable:
            break
        # One sleep per round, long enough for every surviving chain's
        # own pause; ranges whose deadline that pause would cross are
        # abandoned (their last error stands), not slept past.
        pauses = {i: backoffs[i].pause() for i in retryable}
        if rcfg.deadline_s:
            # Deadline contract: the round's shared sleep is max(pause)
            # over the survivors, and since the max itself belongs to a
            # survivor that passed this filter, max(survivor pauses) <=
            # budget — no range is ever reissued past the deadline.
            # (test_mux_retry_deadline_never_oversleeps pins this.)
            budget = rcfg.deadline_s - (_time.monotonic() - start_t)
            retryable = [i for i in retryable if pauses[i] <= budget]
            if not retryable:
                break
        _time.sleep(max(pauses[i] for i in retryable))
        remaining = retryable
    gres = GroupResult(
        errors=[WorkerError(k, e) for k, e in enumerate(final) if e is not None]
    )
    if gres.errors and cfg.workload.abort_on_error:
        raise gres.errors[0]  # errgroup semantics (WorkerGroup parity)
    return gres
