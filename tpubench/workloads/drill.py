"""``tpubench drill`` — the production incident drill: restore-while-
serving on the elastic pod, with delta checkpoint saves.

The composed scenario production actually fears, built from the planes
the last six PRs landed. A threaded hermetic pod serves open-loop
multi-tenant QoS traffic (arrivals plane, admission queue, coop cache);
at ``drill.kill_at_s`` the membership plane KILLS a host (RAM gone, no
goodbye); at ``drill.join_at_s`` a cold replacement joins under the
victim's id and runs a checkpoint restore THROUGH the shared admission
queue — and, on the coop arm, through the coop cache — so restore
reads, peer traffic, and gold-class fetches genuinely contend for
admission slots, cache byte budgets, and (with ``drill.meta_rate_rps``)
metadata quota. Periodic checkpoint DELTA saves (lifecycle/delta.py:
per-shard dirty tracking, ``ifGenerationMatch``-guarded CAS, classified
412 full-save fallback) ride under the same traffic on
``drill.save_interval_s``.

Restore identity is first-class QoS: restore reads carry their own
class tag (``drill.restore_class``) end-to-end — priority in the
admission heap, an owner slot in the cache byte-budget split, their own
ledger/recorder in the scorecard — never a masquerading tenant.

Restore correctness under concurrent saves: each shard's chunk keys are
built at a STAT-PINNED generation, so a delta save landing a new
generation mid-shard surfaces as the pipeline's non-transient
"generation changed under the plan" error (pipeline/prefetch.py) — a
TORN read, counted and re-read at the new generation (bounded by
``drill.restore_retries``), then crc-verified against the generation's
published crc32 (the DeltaTracker map). Byte-identity is proven, not
assumed.

The drill scorecard (``extra["drill"]``) is the robustness headline:
gold SLO during the restore window vs steady state, time-to-restore vs
time-to-rewarm, origin-byte amplification (restore bytes + serve misses
vs checkpoint size), save-pass dispositions (dirty/uploaded/skipped/CAS
conflicts), per-phase blame via the ``delta_commit``/``shard_restored``
flight phases. ``run_drill_sweep`` steps the save interval and locates
the knee. Journals carry a drill replay stamp so ``tpubench record``
makes the whole incident a named, replayable scenario.
"""

from __future__ import annotations

import random
import threading
import time
import zlib
from typing import Optional

from tpubench.config import (
    BenchConfig,
    parse_sleep_scale,
    validate_drill_config,
    validate_serve_config,
)
from tpubench.metrics.percentiles import summarize_ns
from tpubench.metrics.recorder import LatencyRecorder
from tpubench.metrics.report import RunResult
from tpubench.obs.flight import (
    flight_from_config,
    host_journal_path,
    transport_label,
)
from tpubench.obs.telemetry import telemetry_from_config
from tpubench.pipeline.cache import ChunkCache, ChunkKey
from tpubench.pipeline.prefetch import fetch_chunk
from tpubench.serve.qos import (
    AdmissionQueue,
    ClassLedger,
    Request,
    Tenant,
    class_budget_split,
    find_knee,
)
from tpubench.storage import open_backend
from tpubench.storage.base import StorageBackend
from tpubench.workloads.arrivals import scaled_gaps
from tpubench.workloads.serve import (
    _ShedLog,
    _in_windows,
    _merge_windows,
    build_schedule,
    membership_scorecard,
    serve_scorecard,
)

# Push attempts per restore chunk through the admission queue before the
# driver stops re-offering it and fetches direct from origin (counted as
# forced_direct — loud in the scorecard, never a hang).
_MAX_CHUNK_PUSHES = 16


def _payload_bytes(data) -> bytes:
    """Immutable snapshot of a chunk payload (bytes | memoryview |
    SlabLease) — the restore rendezvous needs bytes that outlive the
    worker's ``release_payload``."""
    if hasattr(data, "tobytes"):
        return data.tobytes()
    return bytes(data)


class _RestoreChunk:
    """One in-flight restore read: the driver's rendezvous with whichever
    serve worker (or shed path) resolves it."""

    __slots__ = ("key", "index", "event", "data", "shed", "error")

    def __init__(self, key: ChunkKey):
        self.key = key
        self.index = -1
        self.event = threading.Event()
        self.data: Optional[bytes] = None
        self.shed = False
        self.error: Optional[BaseException] = None


def run_drill(cfg: BenchConfig, backend: Optional[StorageBackend] = None,
              tracer=None, replay_source: Optional[dict] = None,
              save_interval_s: Optional[float] = None) -> RunResult:
    """One incident drill at the configured shape (``save_interval_s``
    is the sweep's per-point override)."""
    validate_serve_config(cfg.serve)
    validate_drill_config(cfg.drill, cfg.serve)
    owns_backend = backend is None
    backend = backend or open_backend(cfg, tracer=tracer)
    try:
        return _Drill(cfg, backend, replay_source=replay_source,
                      save_interval_s=save_interval_s).run()
    finally:
        if owns_backend:
            backend.close()


class _Drill:
    """The composed incident-drill engine — the _ElasticServe shape
    (same pod construction, worker discipline, virtual-time event plan)
    plus the lifecycle arms: baseline save, periodic delta saver,
    scripted kill + cold join, the restore driver, the optional
    meta-storm mix, and the drill scorecard."""

    def __init__(self, cfg: BenchConfig, backend: StorageBackend,
                 replay_source: Optional[dict] = None,
                 save_interval_s: Optional[float] = None):
        self.cfg = cfg
        self.backend = backend
        self.replay_source = replay_source
        self.save_interval_s = (
            cfg.drill.save_interval_s if save_interval_s is None
            else save_interval_s
        )

    def run(self) -> RunResult:  # noqa: PLR0915 — the composed scenario
        from tpubench.dist.membership import ElasticFabric, remap_stats
        from tpubench.lifecycle.delta import DeltaTracker, delta_save
        from tpubench.lifecycle.manifest import build_manifest
        from tpubench.mem.slab import CopyMeter, SlabPool, release_payload
        from tpubench.pipeline.coop import CoopCache, LoopbackChannel
        from tpubench.storage.base import StorageError

        cfg, sc, dc, lc = self.cfg, self.cfg.serve, self.cfg.drill, \
            self.cfg.lifecycle
        backend = self.backend
        victim = dc.victim if dc.victim >= 0 else sc.hosts - 1
        rcls = dc.restore_class
        chunk = sc.chunk_bytes or cfg.workload.granule_bytes
        tlabel = transport_label(cfg)
        scale = parse_sleep_scale("drill arrival gaps")
        flight = flight_from_config(cfg)

        # ---- baseline checkpoint: the state the joiner must restore --
        manifest = build_manifest(lc.prefix, lc.objects, lc.object_bytes)
        tracker = DeltaTracker(manifest)
        save_ring = flight.worker("save") if flight is not None else None
        part_rec = LatencyRecorder("save_part")
        baseline = delta_save(
            backend, tracker, lc.part_bytes, delta=False,
            ring=save_ring, transport_label=tlabel,
            part_recorder=part_rec,
        )
        checkpoint_bytes = sum(s.size for s in manifest.objects)

        objects = backend.list(cfg.workload.object_name_prefix)
        schedule = build_schedule(cfg, backend, None, objects=objects)
        gaps = scaled_gaps([r.arrival_s for r in schedule], scale)

        # ---- QoS surfaces: serving classes + the restore class -------
        qos = sc.qos
        restore_spec = {
            "name": rcls, "share": 0.0, "weight": dc.restore_weight,
            "deadline_ms": dc.restore_deadline_ms,
            "priority": dc.restore_priority,
        }
        all_classes = list(sc.classes) + [restore_spec]
        budgets = class_budget_split(all_classes, cfg.pipeline.cache_bytes) \
            if qos else None
        restore_tenant = Tenant(
            name=f"{rcls}-0", cls=rcls, priority=dc.restore_priority,
            weight=dc.restore_weight, deadline_ms=dc.restore_deadline_ms,
            seed=0,
        )

        shed_log = _ShedLog(flight, tlabel)
        outcome: list = [None] * len(schedule)
        pending: dict[int, _RestoreChunk] = {}
        pending_lock = threading.Lock()

        def _restore_pending(req: Request) -> Optional[_RestoreChunk]:
            with pending_lock:
                return pending.get(req.index)

        def on_shed(req: Request, reason: str) -> None:
            if req.tenant.cls == rcls:
                rc = _restore_pending(req)
                if rc is not None:
                    rc.shed = True
                    rc.event.set()
            else:
                outcome[req.index] = False
            shed_log(req, reason)

        queue = AdmissionQueue(
            cap=sc.admission_cap or sc.workers, qos=qos,
            queue_limit=(sc.queue_limit or 8 * sc.workers) if qos else 0,
            on_shed=on_shed,
        )
        worker_flights = [
            flight.worker(f"serve-{i}") if flight is not None else None
            for i in range(sc.workers)
        ]

        # ---- the pod (the _ElasticServe construction) ----------------
        vnow = [0.0]
        fabric = ElasticFabric(
            sc.hosts, vnodes=cfg.coop.vnodes, clock=lambda: vnow[0],
            flight_ring=(
                flight.worker("member") if flight is not None else None
            ),
        )
        pc = cfg.pipeline
        use_pool = pc.slab_pool and chunk > 0
        slab_bytes = max(chunk, pc.slab_bytes)
        pool_slabs = pc.pool_slabs or 64
        hosts: dict[int, dict] = {}
        retired: list[dict] = []  # replaced host entries (leak accounting)

        def build_host(h: int) -> dict:
            pool = (
                SlabPool(slab_bytes, pool_slabs, use_native=False)
                if use_pool else None
            )
            meter = CopyMeter()
            cache = ChunkCache(pc.cache_bytes, owner_budgets=budgets)

            def origin_fetch(key, _pool=pool, _meter=meter):
                return fetch_chunk(backend, key, pool=_pool, meter=_meter)

            coop = CoopCache(
                cache,
                host_id=h,
                ring=fabric.ring,
                channel=LoopbackChannel(fabric.broker, h),
                origin_fetch=origin_fetch,
                pool=pool,
                meter=meter,
                enabled=True,
                peer_budget_bytes=cfg.coop.peer_budget_bytes,
                retry_cfg=cfg.transport.retry,
                flight_recorder=flight,
            )
            fabric.add_host(coop)
            return {"coop": coop, "cache": cache, "pool": pool,
                    "meter": meter, "origin": origin_fetch}

        for h in range(sc.hosts):
            hosts[h] = build_host(h)

        # ---- the incident plan + the user's extra timeline -----------
        member_plan: list = [
            (dc.kill_at_s, "kill_host", victim),
            (dc.join_at_s, "drill_join", victim),
        ]
        windows: list = [
            [dc.kill_at_s, dc.kill_at_s + sc.resize_window_s],
            [dc.join_at_s, dc.join_at_s + sc.resize_window_s],
        ]
        for t0, t1, spec in sc.membership_timeline:
            (action, host), = spec.items()
            t0, t1 = float(t0), float(t1)
            if action == "pause_host":
                member_plan.append((t0, "pause_host", int(host)))
                member_plan.append((t1, "resume_host", int(host)))
                windows.append([t0, t1 + sc.resize_window_s])
            else:
                member_plan.append((t0, action, int(host)))
                windows.append([t0, t0 + sc.resize_window_s])
        member_plan.sort(key=lambda e: e[0])
        windows = _merge_windows(windows)

        uniq_keys = list({r.key for r in schedule})
        events_out: list = []
        snapshots: list = []

        classes = sorted(
            all_classes, key=lambda c: int(c.get("priority", 0))
        )
        ledgers = {str(c["name"]): ClassLedger() for c in classes}
        recorders = {
            str(c["name"]): LatencyRecorder(f"request_{c['name']}")
            for c in classes
        }
        agg_rec = LatencyRecorder("request")
        ledger_lock = threading.Lock()
        tenant_bytes: dict[str, int] = {}
        completed_bytes = [0]
        failovers = [0]
        no_live_host_errors = [0]
        direct_origin_bytes = [0]

        for req in schedule:
            ledgers[req.tenant.cls].arrivals += 1

        def take_snapshot(t: float) -> None:
            agg = fabric.aggregate()
            with ledger_lock:
                agg["completed"] = sum(
                    led.completed for led in ledgers.values()
                )
                agg["direct_origin_bytes"] = direct_origin_bytes[0]
            snapshots.append((t, agg))

        # ---- restore driver ------------------------------------------
        restore_ring = (
            flight.worker("restore") if flight is not None else None
        )
        restore_stats = {
            "requested": False, "completed": False, "verified": False,
            "shards": len(manifest.objects), "shards_restored": 0,
            "bytes": 0, "chunks": 0, "torn_rereads": 0,
            "shed_repushes": 0, "forced_direct": 0, "errors": 0,
            "started_at_s": None, "finished_at_s": None,
            "time_to_restore_s": None, "via_coop": dc.restore_via_coop,
        }
        restore_done = threading.Event()
        stop_flag = threading.Event()
        rindex = [len(schedule)]  # restore request indices extend the
        # schedule's (outcome[] never sees them — on_shed/worker branch
        # on the restore class first)

        def _push_restore(key: ChunkKey) -> _RestoreChunk:
            rc = _RestoreChunk(key)
            with pending_lock:
                idx = rindex[0]
                rindex[0] += 1
                rc.index = idx
                pending[idx] = rc
            req = Request(
                tenant=restore_tenant, key=key, arrival_s=vnow[0],
                index=idx, host=victim,
            )
            with ledger_lock:
                ledgers[rcls].arrivals += 1
            req.enqueue_ns = time.perf_counter_ns()
            try:
                queue.push(req)
            except Exception:  # noqa: BLE001 — queue closed at the bell
                rc.shed = True
                rc.event.set()
            return rc

        def _restore_shard(spec) -> bool:
            """Restore one shard at a stat-pinned generation; returns
            True when its bytes verified against the published crc."""
            for _attempt in range(dc.restore_retries + 1):
                if stop_flag.is_set():
                    return False
                try:
                    meta = backend.stat(spec.name)
                except StorageError as e:
                    if e.transient:
                        continue  # costs one attempt, never spins
                    restore_stats["errors"] += 1
                    return False
                gen = meta.generation
                keys = [
                    ChunkKey(cfg.workload.bucket, spec.name, gen, start,
                             min(chunk, spec.size - start))
                    for start in range(0, spec.size, chunk)
                ]
                buf = bytearray(spec.size)
                torn = False
                sem = threading.BoundedSemaphore(dc.restore_inflight)
                inflight: list[tuple[_RestoreChunk, int]] = []
                ilock = threading.Lock()

                def _retire(rc: _RestoreChunk) -> None:
                    with pending_lock:
                        pending.pop(rc.index, None)

                def drain_one() -> bool:
                    """Wait out the oldest in-flight chunk; re-push on
                    shed (bounded), direct-fetch past the bound. Every
                    exit releases the inflight slot and retires the
                    rendezvous entry. Returns False on torn generation
                    (caller abandons the attempt and re-stats)."""
                    with ilock:
                        rc, pushes = inflight.pop(0)
                    try:
                        while True:
                            while not rc.event.wait(timeout=0.25):
                                if stop_flag.is_set():
                                    return True
                            _retire(rc)
                            if rc.error is not None:
                                err = rc.error
                                if (isinstance(err, StorageError)
                                        and "generation" in str(err)):
                                    return False  # torn: re-stat
                                restore_stats["errors"] += 1
                                return True
                            if rc.shed:
                                if pushes >= _MAX_CHUNK_PUSHES \
                                        or stop_flag.is_set():
                                    restore_stats["forced_direct"] += 1
                                    try:
                                        data = fetch_chunk(backend, rc.key)
                                    except StorageError:
                                        return False
                                    with ledger_lock:
                                        direct_origin_bytes[0] += len(data)
                                    buf[rc.key.start:rc.key.start
                                        + len(data)] = _payload_bytes(data)
                                    release_payload(data)
                                    return True
                                restore_stats["shed_repushes"] += 1
                                rc = _push_restore(rc.key)
                                pushes += 1
                                continue
                            buf[rc.key.start:rc.key.start
                                + len(rc.data)] = rc.data
                            restore_stats["chunks"] += 1
                            return True
                    finally:
                        _retire(rc)
                        sem.release()

                ok = True
                for key in keys:
                    while not sem.acquire(timeout=0.25):
                        if stop_flag.is_set():
                            return False
                    with ilock:
                        inflight.append((_push_restore(key), 0))
                    # Opportunistically reap ahead of the window edge.
                    while True:
                        with ilock:
                            ready = (inflight
                                     and inflight[0][0].event.is_set())
                        if not ready:
                            break
                        if not drain_one():
                            ok = False
                            break
                    if not ok:
                        break
                while ok:
                    with ilock:
                        empty = not inflight
                    if empty:
                        break
                    if not drain_one():
                        ok = False
                if not ok or stop_flag.is_set():
                    # Abandoned attempt: retire any still-in-flight
                    # rendezvous entries (their workers complete the
                    # reads as ordinary restore-class requests).
                    with ilock:
                        leftovers = list(inflight)
                        inflight.clear()
                    for rc, _ in leftovers:
                        _retire(rc)
                    if not stop_flag.is_set():
                        restore_stats["torn_rereads"] += 1
                        continue
                    return False
                crc = zlib.crc32(bytes(buf)) & 0xFFFFFFFF
                want = tracker.crc_for(spec.name, gen)
                if want is None or crc != want:
                    # Foreign/raced generation or torn assembly: the
                    # byte-identity check failed — re-stat and re-read.
                    restore_stats["torn_rereads"] += 1
                    continue
                restore_stats["bytes"] += spec.size
                restore_stats["shards_restored"] += 1
                if restore_ring is not None:
                    op = restore_ring.begin(spec.name, tlabel,
                                            kind="object")
                    op.note("restore_shard", generation=gen,
                            size=spec.size)
                    op.mark("shard_restored")
                    op.finish(0)
                return True
            restore_stats["errors"] += 1
            return False

        def restore_driver() -> None:
            restore_stats["requested"] = True
            restore_stats["started_at_s"] = vnow[0]
            t0 = time.perf_counter_ns()
            ok = True
            try:
                for spec in manifest.objects:
                    if not _restore_shard(spec):
                        ok = False
                        if stop_flag.is_set():
                            break
            except Exception:  # noqa: BLE001 — a dead restore is a drill
                # RESULT (scored as unverified), never a hung run
                restore_stats["errors"] += 1
                ok = False
            finally:
                restore_stats["time_to_restore_s"] = (
                    (time.perf_counter_ns() - t0) / 1e9
                )
                restore_stats["finished_at_s"] = vnow[0]
                restore_stats["completed"] = (
                    restore_stats["shards_restored"]
                    == restore_stats["shards"]
                )
                restore_stats["verified"] = (
                    ok and restore_stats["completed"]
                )
                restore_done.set()

        restore_thread = threading.Thread(
            target=restore_driver, name="drill-restore", daemon=True,
        )

        # ---- delta saver (rides virtual time) ------------------------
        save_passes: list[dict] = []
        saver_stop = threading.Event()
        dirty_rng = random.Random(lc.seed + 17)

        def saver() -> None:
            interval = self.save_interval_s
            if interval <= 0:
                return
            next_t = interval
            while not saver_stop.is_set():
                if vnow[0] >= next_t:
                    tracker.mutate(dirty_rng, dc.dirty_fraction)
                    try:
                        save_passes.append(delta_save(
                            backend, tracker, lc.part_bytes,
                            delta=dc.delta_saves, ring=save_ring,
                            transport_label=tlabel,
                            part_recorder=part_rec,
                        ))
                    except Exception:  # noqa: BLE001 — a failed pass is
                        # data (delta_save already classifies per-shard
                        # errors; total failure counts as a zero pass)
                        save_passes.append({"errors": 1})
                    next_t += interval
                else:
                    saver_stop.wait(0.005)

        saver_thread = threading.Thread(
            target=saver, name="drill-saver", daemon=True,
        )

        # ---- concurrent metadata storm (shared ledger) ---------------
        storm_out: dict = {}
        storm_thread = None
        if dc.meta_rate_rps > 0:
            from tpubench.lifecycle.storm import StormLedger
            from tpubench.workloads.meta_storm import (
                _storm_point,
                populate_meta_objects,
            )

            meta_names = populate_meta_objects(
                backend, lc.prefix, lc.meta_objects, lc.meta_object_bytes,
            )
            storm_ledger = StormLedger()

            def storm() -> None:
                try:
                    storm_out["result"] = _storm_point(
                        cfg, backend, meta_names, dc.meta_rate_rps,
                        flight, tlabel, ledger=storm_ledger,
                    )
                except Exception as e:  # noqa: BLE001 — storm failure
                    # degrades the drill's metadata arm, never the run
                    storm_out["error"] = repr(e)

            storm_thread = threading.Thread(
                target=storm, name="drill-storm", daemon=True,
            )

        # ---- membership event application ----------------------------
        def apply_event(t: float, action: str, host: int) -> None:
            vnow[0] = max(vnow[0], t)
            before = fabric.owners_of(uniq_keys)
            handoff = None
            if action == "kill_host":
                ok = fabric.kill_host(host)
            elif action == "drill_join":
                # The cold replacement: a FRESH cache + coop under the
                # victim's id (its RAM died with it), registered with
                # the fabric, then a membership join — and the restore
                # driver starts the moment the joiner is live.
                retired.append(hosts[host])
                hosts[host] = build_host(host)
                ok = fabric.rejoin_host(host)
                restore_thread.start()
            elif action == "leave_host":
                handoff = fabric.leave_host(host)
                ok = handoff is not None
            elif action == "pause_host":
                ok = fabric.pause_host(host)
            elif action == "resume_host":
                ok = fabric.resume_host(host)
            elif action == "rejoin_host":
                ok = fabric.rejoin_host(host)
            else:  # unreachable under validate_membership_timeline
                ok = False
            ev = {
                "t_s": t, "action": action, "host": host, "applied": ok,
                "epoch": fabric.membership.epoch,
            }
            ev.update(remap_stats(
                uniq_keys, before, fabric.owners_of(uniq_keys)
            ))
            if handoff is not None:
                ev["handoff"] = handoff
            events_out.append(ev)
            take_snapshot(t)

        # ---- telemetry -----------------------------------------------
        jpath_stream = None
        if cfg.obs.flight_journal:
            jpath_stream = host_journal_path(
                cfg.obs.flight_journal, cfg.dist.process_id,
                cfg.dist.num_processes,
            )
        tel = telemetry_from_config(cfg)
        tel_summary = None
        if tel is not None:
            tel.resource["workload"] = "drill"
            if flight is not None:
                tel.attach_flight(flight)
                if jpath_stream:
                    tel.stream_journal(
                        flight, jpath_stream,
                        extra_fn=lambda: {"workload": "drill"},
                        max_bytes=cfg.obs.journal_max_bytes,
                    )
            tel.attach_recorders([agg_rec])
            tel.start()

        # ---- the service worker (the _ElasticServe discipline, plus
        # the restore rendezvous and the coop-vs-direct restore arm) ---
        def worker(i: int) -> None:
            wf = worker_flights[i]
            while True:
                req = queue.pop()
                if req is None:
                    return
                cls = req.tenant.cls
                is_restore = cls == rcls
                t_pop = time.perf_counter_ns()
                op = None
                try:
                    host = req.host
                    if not fabric.is_dispatchable(host):
                        live = sorted(fabric.live_hosts())
                        if not live:
                            with ledger_lock:
                                no_live_host_errors[0] += 1
                            raise StorageError(
                                "no live hosts in the pod",
                                transient=False,
                            )
                        host = live[req.index % len(live)]
                        with ledger_lock:
                            failovers[0] += 1
                    entry = hosts[host]
                    cache, coop = entry["cache"], entry["coop"]
                    data = cache.get(req.key)
                    if data is not None:
                        source = "hit"
                        if wf is not None:
                            op = wf.begin(
                                req.key.object, tlabel, kind="cache",
                                enqueue_ns=req.enqueue_ns,
                            )
                            op.mark("cache_hit")
                    else:
                        if wf is not None:
                            op = wf.begin(
                                req.key.object, tlabel,
                                enqueue_ns=req.enqueue_ns,
                            )
                            op.mark("cache_miss", t_pop)
                        if is_restore and not dc.restore_via_coop:
                            # Direct-to-origin arm: the restore read
                            # bypasses coop routing (no peer hits, no
                            # pod single-flight) but still holds an
                            # admission slot and a cache budget — the
                            # A/B isolates the coop's contribution.
                            def _direct(k=req.key, e=entry):
                                d = e["origin"](k)
                                with ledger_lock:
                                    direct_origin_bytes[0] += len(d)
                                return d

                            fetcher = _direct
                        else:
                            fetcher = (
                                lambda k=req.key, c=coop: c.fetch(k)
                            )
                        data, source = cache.get_or_fetch_info(
                            req.key, fetcher,
                            owner=cls if qos else None,
                        )
                        if op is not None:
                            if source == "hit":
                                # Raced hit (the serve discipline): the
                                # would-be miss record becomes a cache
                                # record so the fetcher stays the only
                                # byte-carrying one.
                                op.abandon()
                                op = wf.begin(
                                    req.key.object, tlabel, kind="cache",
                                    enqueue_ns=req.enqueue_ns,
                                )
                                op.mark("cache_hit")
                            else:
                                op.mark("body_complete")
                    done_ns = time.perf_counter_ns()
                    met = done_ns <= req.deadline_ns
                    nbytes = len(data)
                    if is_restore:
                        rc = _restore_pending(req)
                        if rc is not None:
                            rc.data = _payload_bytes(data)
                            rc.event.set()
                    release_payload(data)
                    if op is not None:
                        op.note(
                            "serve_req", cls=cls, outcome="completed",
                            deadline_met=met, host=host,
                        )
                        op.finish(
                            nbytes if source in ("hit", "fetched") else 0
                        )
                    lat_ns = done_ns - req.enqueue_ns
                    with ledger_lock:
                        led = ledgers[cls]
                        led.completed += 1
                        led.bytes += nbytes
                        if met:
                            led.deadline_met += 1
                        tenant_bytes[req.tenant.name] = (
                            tenant_bytes.get(req.tenant.name, 0) + nbytes
                        )
                        completed_bytes[0] += nbytes
                    if not is_restore:
                        outcome[req.index] = bool(met)
                    recorders[cls].record_ns(lat_ns)
                    agg_rec.record_ns(lat_ns)
                except Exception as e:  # noqa: BLE001 — per-request domain
                    if op is not None:
                        op.finish(error=e)
                    if is_restore:
                        rc = _restore_pending(req)
                        if rc is not None:
                            rc.error = e
                            rc.event.set()
                    else:
                        outcome[req.index] = False
                    with ledger_lock:
                        ledgers[cls].errors += 1
                finally:
                    queue.done()

        threads = [
            threading.Thread(target=worker, args=(i,),
                             name=f"drill-{i}", daemon=True)
            for i in range(sc.workers)
        ]
        activation = flight.activate() if flight is not None else None
        t0 = time.perf_counter_ns()
        try:
            if activation is not None:
                activation.__enter__()
            for t in threads:
                t.start()
            saver_thread.start()
            if storm_thread is not None:
                storm_thread.start()
            take_snapshot(0.0)
            # ---- the open loop, incident interleaved -----------------
            mp_i = 0
            snap_every = max(1, len(schedule) // 64)
            rr = 0
            for req, gap in zip(schedule, gaps):
                while (mp_i < len(member_plan)
                       and member_plan[mp_i][0] <= req.arrival_s):
                    apply_event(*member_plan[mp_i])
                    mp_i += 1
                if gap > 0:
                    time.sleep(gap)
                vnow[0] = max(vnow[0], req.arrival_s)
                live = sorted(fabric.live_hosts())
                req.host = live[rr % len(live)] if live else -1
                rr += 1
                req.enqueue_ns = time.perf_counter_ns()
                queue.push(req)
                if rr % snap_every == 0:
                    take_snapshot(req.arrival_s)
            while mp_i < len(member_plan):
                apply_event(*member_plan[mp_i])
                mp_i += 1
            vnow[0] = max(vnow[0], sc.duration_s)
            # Grace: serve drain + the restore's own completion bound.
            grace_s = max(1.0, 2.0 * scale)
            t_end_ns = time.perf_counter_ns() + int(grace_s * 1e9)
            while (queue.queued or queue.in_service) \
                    and time.perf_counter_ns() < t_end_ns:
                time.sleep(0.005)
            if restore_stats["requested"]:
                restore_done.wait(timeout=max(5.0, 10.0 * scale))
        finally:
            stop_flag.set()
            saver_stop.set()
            drained = queue.close()
            for t in threads:
                t.join(timeout=5.0)
            saver_thread.join(timeout=5.0)
            if restore_stats["requested"]:
                restore_thread.join(timeout=5.0)
            if storm_thread is not None:
                storm_thread.join(timeout=max(5.0, 10.0 * scale))
            take_snapshot(max(vnow[0], sc.duration_s))
            if activation is not None:
                activation.__exit__(None, None, None)
            if tel is not None:
                tel.set_chips(1)
                tel_summary = tel.close()
        wall = (time.perf_counter_ns() - t0) / 1e9

        # ---- teardown: every host entry ever built (leak detection) --
        per_host = []
        pool_leaks = 0
        fabric.close()
        for h, entry in sorted(hosts.items()):
            stats = {"host": h, "coop": entry["coop"].stats(),
                     "cache": entry["cache"].stats(),
                     "copies": entry["meter"].stats()}
            entry["cache"].close()
            if entry["pool"] is not None:
                ps = entry["pool"].close()
                pool_leaks += ps.get("leaked_slabs", 0)
                stats["pool"] = ps
            per_host.append(stats)
        for entry in retired:
            entry["cache"].close()
            if entry["pool"] is not None:
                ps = entry["pool"].close()
                pool_leaks += ps.get("leaked_slabs", 0)

        qstats = queue.stats()
        qstats["drained_at_close"] = drained
        for reason, by_cls in qstats["shed"].items():
            for cls, n in by_cls.items():
                if cls in ledgers:
                    ledgers[cls].shed += n

        serve_extra = serve_scorecard(
            sc, schedule, ledgers, recorders, tenant_bytes, qstats,
            wall, completed_bytes[0], classes,
        )
        membership = membership_scorecard(
            sc, schedule, outcome, events_out, windows, snapshots,
            per_host, failovers[0], no_live_host_errors[0], pool_leaks,
            [c for c in classes if str(c["name"]) != rcls], fabric,
        )
        drill_extra = self._drill_scorecard(
            schedule, outcome, restore_stats, save_passes, baseline,
            checkpoint_bytes, snapshots, direct_origin_bytes[0],
            events_out, storm_out, part_rec,
        )

        summaries = {}
        if len(agg_rec):
            summaries["request"] = summarize_ns(agg_rec.as_ns_array())
        for cls, rec in recorders.items():
            if len(rec):
                summaries[f"request_{cls}"] = summarize_ns(
                    rec.as_ns_array()
                )
        gbps = (completed_bytes[0] / 1e9) / wall if wall > 0 else 0.0
        errors = sum(led.errors for led in ledgers.values())
        res = RunResult(
            workload="drill",
            config=cfg.to_dict(),
            bytes_total=completed_bytes[0],
            wall_seconds=wall,
            gbps=gbps,
            gbps_per_chip=gbps,
            n_chips=1,
            summaries=summaries,
            errors=errors,
        )
        res.extra["serve"] = serve_extra
        res.extra["membership"] = membership
        res.extra["drill"] = drill_extra
        if tel_summary is not None:
            res.extra["telemetry"] = tel_summary
        from tpubench.storage.tail import collect_tail_stats

        tail_stats = collect_tail_stats(backend)
        if tail_stats:
            res.extra["tail"] = tail_stats
        if flight is not None:
            res.extra["flight"] = flight.summary()
            if jpath_stream:
                from tpubench.replay.bundle import (
                    drill_replay_plan,
                    journal_replay_stamp,
                )

                s = summaries.get("request")
                # A replayed drill re-stamps the ORIGINAL bundle's
                # drill block (plan/shape rebuild identically; the
                # baseline must stay the original's) so record →
                # replay → record converges.
                src_drill = (self.replay_source or {}).get("drill")
                res.extra["flight_journal"] = flight.write_journal(
                    jpath_stream,
                    extra={
                        "workload": "drill", "n_chips": 1,
                        "replay": journal_replay_stamp(
                            cfg, schedule, objects, serve_extra,
                            rate_rps=sc.rate_rps,
                            membership=membership,
                            drill=src_drill or drill_replay_plan(
                                cfg, drill_extra, self.save_interval_s,
                            ),
                            errors=errors,
                            p99_ms=s.p99_ms if s is not None else None,
                            source=self.replay_source,
                        ),
                    },
                    max_bytes=cfg.obs.journal_max_bytes,
                )
        return res

    # --------------------------------------------------- scorecard ----
    def _drill_scorecard(self, schedule, outcome, restore_stats,
                         save_passes, baseline, checkpoint_bytes,
                         snapshots, direct_bytes, events_out, storm_out,
                         part_rec) -> dict:
        sc, dc = self.cfg.serve, self.cfg.drill

        # Gold SLO during the restore window vs steady state — by
        # ARRIVAL time (the membership-scorecard convention). The
        # restore window is [join, restore completion] in virtual time;
        # an unfinished restore extends it to end-of-run.
        r_end = restore_stats["finished_at_s"]
        w_end = (
            r_end if (r_end is not None and restore_stats["completed"])
            else sc.duration_s
        )
        # At least the resize window wide: a fast restore would
        # otherwise leave the SLO cell with no arrivals to judge.
        window = [(
            dc.join_at_s,
            max(w_end, dc.join_at_s + sc.resize_window_s),
        )]
        tally: dict = {}
        for req in schedule:
            seg = "restore_window" \
                if _in_windows(req.arrival_s, window) else "steady"
            met, tot = tally.get((seg, req.tenant.cls), (0, 0))
            tally[(seg, req.tenant.cls)] = (
                met + (1 if outcome[req.index] else 0), tot + 1
            )
        slo: dict = {"restore_window": {}, "steady": {}}
        for c in sc.classes:
            cls = str(c["name"])
            for seg in ("restore_window", "steady"):
                met, tot = tally.get((seg, cls), (0, 0))
                slo[seg][cls] = (met / tot) if tot else None

        # Origin-byte amplification: what the incident actually cost in
        # origin reads (coop-counted origin fetches + direct restore
        # fetches) against the checkpoint's own size.
        def value_at(t: float, key: str) -> int:
            v = 0
            for st, agg in snapshots:
                if st <= t:
                    v = agg.get(key, 0)
                else:
                    break
            return v

        last = snapshots[-1][1] if snapshots else {}
        origin_total = (last.get("origin_bytes", 0)
                        + last.get("direct_origin_bytes", 0))
        w0, w1 = window[0]
        w1c = min(w1, sc.duration_s)
        restore_window_origin = (
            (value_at(w1c, "origin_bytes")
             + value_at(w1c, "direct_origin_bytes"))
            - (value_at(w0, "origin_bytes")
               + value_at(w0, "direct_origin_bytes"))
        ) if snapshots else 0

        # Save-pass aggregation (the delta ledger the acceptance test
        # asserts against: delta passes upload ONLY dirty shards).
        agg_saves = {
            "passes": len(save_passes),
            "interval_s": (
                self.save_interval_s if self.save_interval_s > 0
                else None
            ),
            "delta": dc.delta_saves,
            "uploaded_shards": 0, "dirty_shards": 0, "skipped_clean": 0,
            "cas_conflicts": 0, "full_fallbacks": 0,
            "bytes_uploaded": 0, "errors": 0,
        }
        for p in save_passes:
            for k in ("uploaded_shards", "dirty_shards", "skipped_clean",
                      "cas_conflicts", "full_fallbacks", "bytes_uploaded",
                      "errors"):
                agg_saves[k] += p.get(k, 0)

        # Time-to-rewarm for the kill event (the membership scorecard
        # computed it onto the event dict) vs time-to-restore.
        rewarm = None
        for ev in events_out:
            if ev["action"] == "kill_host":
                rewarm = ev.get("time_to_rewarm_s")
                break

        part = None
        if len(part_rec):
            p = summarize_ns(part_rec.as_ns_array())
            part = {"p50_ms": p.p50_ms, "p99_ms": p.p99_ms,
                    "count": len(part_rec)}
        meta = None
        if storm_out:
            r = storm_out.get("result")
            meta = {"error": storm_out["error"]} \
                if "error" in storm_out else {
                    k: r[k] for k in (
                        "ops", "completed", "errors", "offered_rps",
                        "achieved_rps", "p50_ms", "p99_ms",
                    )
                }

        restore = dict(restore_stats)
        return {
            "arm": {
                "restore_via_coop": dc.restore_via_coop,
                "delta_saves": dc.delta_saves,
            },
            "incident": {
                "kill_at_s": dc.kill_at_s, "join_at_s": dc.join_at_s,
                "victim": (dc.victim if dc.victim >= 0
                           else sc.hosts - 1),
            },
            "restore_class": dc.restore_class,
            "restore": restore,
            "saves": agg_saves,
            "baseline_save": baseline,
            "gold_slo": slo,
            "restore_window_s": [w0, w1c],
            "time_to_rewarm_s": rewarm,
            "amplification": {
                "checkpoint_bytes": checkpoint_bytes,
                "restore_bytes": restore_stats["bytes"],
                "restore_window_origin_bytes": restore_window_origin,
                "origin_bytes_total": origin_total,
                "ratio": (origin_total / checkpoint_bytes)
                if checkpoint_bytes else None,
            },
            "save_part_latency": part,
            "meta": meta,
        }


def run_drill_sweep(cfg: BenchConfig, tracer=None) -> RunResult:
    """``tpubench drill --drill-sweep``: step the save interval through
    ``drill.sweep_points × save_interval_s`` and emit the save-rate-vs-
    latency curve with the knee identified — where saving more often
    starts costing the gold SLO."""
    validate_serve_config(cfg.serve)
    validate_drill_config(cfg.drill, cfg.serve)
    points = []
    results = []
    base = cfg.drill.save_interval_s or 1.0
    # Largest interval first: the knee detector walks points in
    # ASCENDING offered (save) rate and compares against the lightest
    # point's p99.
    for i, mult in enumerate(
        sorted(cfg.drill.sweep_points, reverse=True)
    ):
        c = BenchConfig.from_dict(cfg.to_dict())
        # Per-point endpoint churn off (the serve-sweep policy): one
        # sweep must not bind N telemetry ports.
        c.telemetry.port = -1
        c.telemetry.enabled = False
        c.telemetry.otlp = False
        if c.obs.flight_journal:
            c.obs.flight_journal = f"{c.obs.flight_journal}.pt{i}"
        interval = base * float(mult)
        res = run_drill(c, tracer=tracer, save_interval_s=interval)
        d = res.extra["drill"]
        sv = res.extra["serve"]
        gold = next(
            (str(cc["name"]) for cc in sorted(
                cfg.serve.classes,
                key=lambda cc: int(cc.get("priority", 0)),
            )), None,
        )
        gold_cls = sv["classes"].get(gold, {}) if gold else {}
        passes = d["saves"]["passes"]
        points.append({
            "save_interval_s": interval,
            # The knee detector's axes: offered load is the SAVE rate
            # (passes/s grows as the interval shrinks), achieved is the
            # save passes the run actually landed, latency is the gold
            # class's own p99 under that save pressure.
            "offered_rps": 1.0 / interval if interval > 0 else 0.0,
            "achieved_rps": (
                passes / cfg.serve.duration_s
                if cfg.serve.duration_s > 0 else None
            ),
            "p99_ms": gold_cls.get("p99_ms"),
            "goodput_gbps": sv.get("goodput_gbps", 0.0),
            "gold_slo_restore_window": (
                d["gold_slo"]["restore_window"].get(gold)
                if gold else None
            ),
            "time_to_restore_s": d["restore"]["time_to_restore_s"],
            "save_passes": passes,
            "bytes_uploaded": d["saves"]["bytes_uploaded"],
            "cas_conflicts": d["saves"]["cas_conflicts"],
        })
        results.append(res)
    knee = find_knee(points)
    out = results[-1]
    out.extra["drill_sweep"] = {"points": points, "knee": knee}
    return out


# ----------------------------------------------------------- rendering ----
def format_drill_scorecard(d: dict) -> str:
    """Human rendering of ``extra["drill"]`` — shared by the CLI and
    ``tpubench report``, jax-free like every report surface."""
    arm = d.get("arm") or {}
    inc = d.get("incident") or {}
    rst = d.get("restore") or {}
    sv = d.get("saves") or {}
    amp = d.get("amplification") or {}
    lines = [
        "  incident drill scorecard "
        f"[restore {'via coop' if arm.get('restore_via_coop') else 'direct'}"
        f", {'delta' if arm.get('delta_saves') else 'full'} saves]:",
        f"    kill host {inc.get('victim')} @ {inc.get('kill_at_s')}s  "
        f"cold join @ {inc.get('join_at_s')}s  "
        f"restore class={d.get('restore_class')!r}",
    ]
    ttr = rst.get("time_to_restore_s")
    rewarm = d.get("time_to_rewarm_s")
    lines.append(
        f"    time-to-restore="
        f"{'%.3f s' % ttr if ttr is not None else '—'}  "
        f"time-to-rewarm="
        f"{'%.3f s' % rewarm if rewarm is not None else '—'}  "
        f"verified={rst.get('verified')}  "
        f"shards={rst.get('shards_restored')}/{rst.get('shards')}"
    )
    lines.append(
        f"    restore: chunks={rst.get('chunks', 0)}  "
        f"torn_rereads={rst.get('torn_rereads', 0)}  "
        f"shed_repushes={rst.get('shed_repushes', 0)}  "
        f"forced_direct={rst.get('forced_direct', 0)}  "
        f"errors={rst.get('errors', 0)}"
    )
    slo = d.get("gold_slo") or {}
    for seg in ("restore_window", "steady"):
        cells = []
        for cls, v in sorted((slo.get(seg) or {}).items()):
            cells.append(
                f"{cls}={'%.1f%%' % (100 * v) if v is not None else '—'}"
            )
        lines.append(f"    slo[{seg}]: " + "  ".join(cells))
    lines.append(
        f"    saves: passes={sv.get('passes', 0)} "
        f"(interval={sv.get('interval_s')}s)  "
        f"uploaded={sv.get('uploaded_shards', 0)}  "
        f"dirty={sv.get('dirty_shards', 0)}  "
        f"skipped_clean={sv.get('skipped_clean', 0)}"
    )
    lines.append(
        f"    cas_conflicts={sv.get('cas_conflicts', 0)}  "
        f"full_fallbacks={sv.get('full_fallbacks', 0)}  "
        f"save_bytes={sv.get('bytes_uploaded', 0)}  "
        f"save_errors={sv.get('errors', 0)}"
    )
    ratio = amp.get("ratio")
    lines.append(
        f"    amplification: checkpoint={amp.get('checkpoint_bytes', 0)}  "
        f"restore={amp.get('restore_bytes', 0)}  "
        f"origin_total={amp.get('origin_bytes_total', 0)}  "
        f"ratio={'%.2fx' % ratio if ratio is not None else '—'}"
    )
    meta = d.get("meta")
    if meta:
        if "error" in meta:
            lines.append(f"    meta-storm: failed ({meta['error']})")
        else:
            lines.append(
                f"    meta-storm: ops={meta.get('ops', 0)}  "
                f"completed={meta.get('completed', 0)}  "
                f"errors={meta.get('errors', 0)}  "
                f"p99={meta.get('p99_ms', 0.0):.2f} ms"
            )
    return "\n".join(lines)


def format_drill_sweep(ds: dict) -> str:
    """Human rendering of ``extra["drill_sweep"]``."""
    lines = ["  save-interval sweep:"]
    for p in ds.get("points", []):
        slo = p.get("gold_slo_restore_window")
        lines.append(
            f"    interval={p['save_interval_s']:.3g}s  "
            f"passes={p.get('save_passes', 0)}  "
            f"save_bytes={p.get('bytes_uploaded', 0)}  "
            f"gold_slo_restore="
            f"{'%.1f%%' % (100 * slo) if slo is not None else '—'}  "
            f"p99={p.get('p99_ms') or 0.0:.2f} ms"
        )
    knee = ds.get("knee")
    if knee:
        lines.append(
            f"    knee @ save rate {knee.get('offered_rps', 0.0):.3g}/s "
            f"({knee.get('reason', '')})"
        )
    return "\n".join(lines)
