"""Read workload on the C++ fetch executor (``tb_pool_*``) — the
reference's errgroup fan-out (``main.go:200-212``) in native code, with the
client-level retry policy (``main.go:179-184``) applied to completions and,
in staged mode, the flagship GCS→HBM pipeline fed directly from the
executor.

Executor dispatch shape (``--fetch-executor``): ``native`` runs the epoll
REACTOR (one event loop owning all connections, completions over lock-free
SPSC rings — the post-BENCH_r05 default; that bench measured the legacy
thread-per-connection pool LOSING to the Python hot loop because every
completion paid a mutex/condvar crossing); ``native-threads`` pins the
legacy pool (still the TLS path and the A/B comparator);
``native-reactor`` pins the reactor explicitly. The runnable-queue
admission cap, the live tune knobs (``workers`` actuation) and the retry
scheduler are pool-shape-agnostic and survive either dispatch.

Two runners:

* :func:`run_read_native_executor` — staging "none": measures pure fetch
  fan-out (host-RAM parity with ``io.Discard``, main.go:140). Worker *i*
  owns object ``<prefix><i>`` with ONE outstanding read (the serial
  per-worker loop's concurrency shape); dispatch, keep-alive, receive and
  timing run on pool pthreads; Python only drains completions.

* :func:`run_read_native_staged` — staging "device_put": the object is
  range-sharded at STAGING-SLOT granularity; each pool task lands one
  slot-sized byte range straight into a staging slot's posix_memalign'd
  buffer (socket → slot, zero copies), and the slot ships to HBM with one
  async ``jax.device_put``. Python's only per-slot work is that one launch
  — one interpreter touch per ``slot_bytes`` (default 8-16 MB), not per
  granule or per socket read. This is the executor equivalent of the
  Python zero-copy sink path (``staging/device.py``), with the fetch hot
  loop fully native.

Retry semantics (both runners): completions that classify as failures
re-enter the submit queue under the gax policy (``storage/retry.py``
semantics: jittered exponential backoff, 30 s cap, x2.0; policy
"always"/"idempotent"/"never"; optional attempt cap and deadline from
``transport.retry``) — not just the executor's built-in one
stale-connection retransmit. Backoff pauses are served by the completion
wait's timeout, so a worker awaiting backoff never blocks the drain loop.
"""

from __future__ import annotations

import heapq
import time
from typing import Optional

from tpubench.config import BenchConfig, RetryConfig
from tpubench.metrics import MetricSet
from tpubench.metrics.report import RunResult
from tpubench.storage.base import StorageBackend

# The one transient-status ABI, shared with the Python client path — a
# second hand-maintained copy would drift.
from tpubench.storage.gcs_http import _TRANSIENT as _TRANSIENT_HTTP


def _classify(result: int, status: int, permanent_codes) -> str:
    """'ok' | 'transient' | 'permanent' for one executor completion.

    Same classification the Python path applies via StorageError.transient:
    negative engine codes split on the PERMANENT_CODES ABI (socket errnos
    and short bodies transient, protocol-shape permanent); HTTP statuses
    split on the 408/429/5xx set.
    """
    if result < 0:
        return "permanent" if result in permanent_codes else "transient"
    if status in (200, 206):
        return "ok"
    return "transient" if status in _TRANSIENT_HTTP else "permanent"


class RetryScheduler:
    """gax backoff over executor completions.

    Tracks per-task attempt counts and Backoff state; failed tasks are
    ``push()``ed and come back from ``pop_due()`` when their jittered pause
    elapses. ``next_due_in_ms`` feeds the completion wait's timeout so
    pauses cost no busy-waiting and never block other workers' completions.
    """

    def __init__(self, cfg: RetryConfig, clock=time.monotonic):
        from tpubench.storage.retry import Backoff

        self._cfg = cfg
        self._clock = clock
        self._backoff_cls = Backoff
        # key -> (attempts, Backoff, chain_start): deadline_s is measured
        # from each task's OWN first failure (retry_call measures from each
        # call's start), not from run start — a long run must not stop
        # retrying late tasks just because the run is old.
        self._state: dict[int, tuple[int, object, float]] = {}
        self._heap: list[tuple[float, int, object]] = []
        self.retries = 0

    def offer(self, key: int, verdict: str) -> Optional[float]:
        """Decide whether task ``key`` (which failed with ``verdict``) may
        retry. Returns the pause in seconds, or None = give up (policy
        forbids, attempts exhausted, or deadline passed). Mirrors
        ``retry_call``: policy "always" retries any storage-level failure,
        "idempotent" only transient ones, "never" none.
        """
        cfg = self._cfg
        if cfg.policy == "never":
            return None
        if cfg.policy == "idempotent" and verdict != "transient":
            return None
        now = self._clock()
        attempts, backoff, chain_start = self._state.get(key, (0, None, now))
        if backoff is None:
            backoff = self._backoff_cls(cfg)
        attempts += 1
        if cfg.max_attempts and attempts >= cfg.max_attempts:
            return None
        pause = backoff.pause()
        if cfg.deadline_s and (now - chain_start) + pause > cfg.deadline_s:
            return None
        self._state[key] = (attempts, backoff, chain_start)
        return pause

    def push(self, key: int, item, pause: float) -> None:
        heapq.heappush(self._heap, (self._clock() + pause, key, item))
        self.retries += 1

    def done(self, key: int) -> None:
        self._state.pop(key, None)

    def pop_due(self) -> list:
        now = self._clock()
        due = []
        while self._heap and self._heap[0][0] <= now:
            _, _, item = heapq.heappop(self._heap)
            due.append(item)
        return due

    @property
    def waiting(self) -> int:
        return len(self._heap)

    def next_due_in_ms(self, cap_ms: int) -> int:
        """Completion-wait timeout: min(cap, time to the next due retry)."""
        if not self._heap:
            return cap_ms
        ms = int((self._heap[0][0] - self._clock()) * 1000) + 1
        return max(1, min(cap_ms, ms))


def _require_native_http(cfg: BenchConfig, backend: StorageBackend):
    """Shared preconditions: the executor speaks HTTP/1.1 over plaintext
    or TLS; returns (engine, inner GcsHttpBackend)."""
    from tpubench.native.engine import get_engine
    from tpubench.storage.gcs_http import GcsHttpBackend

    engine = get_engine()
    if engine is None:
        raise RuntimeError(
            "workload.fetch_executor='native' but the native engine is "
            "unavailable (C++ toolchain missing?)"
        )
    # Unwrap the whole decorator chain (retry → tail → reactor-fetch):
    # the runners need the raw GcsHttpBackend for native_request_parts.
    inner = backend
    while not isinstance(inner, GcsHttpBackend) and hasattr(inner, "inner"):
        inner = inner.inner
    if not isinstance(inner, GcsHttpBackend) or inner.scheme not in (
        "http", "https",
    ):
        raise ValueError(
            "fetch_executor='native' requires --protocol http (plain or "
            "https endpoint)"
        )
    if inner.scheme == "https" and not engine.tls_available():
        raise RuntimeError(
            "fetch_executor='native' on an https endpoint, but the engine "
            "could not load OpenSSL (libssl.so.3)"
        )
    if inner.transport.http2 and executor_mode(
        cfg.workload.fetch_executor
    ) != "reactor":
        # Only the reactor multiplexes h2 streams; the legacy pool speaks
        # HTTP/1.1. Running it under an http2=True config would silently
        # mislabel the h1-vs-h2 A/B.
        raise ValueError(
            "fetch_executor='native-threads' fetches over HTTP/1.1; "
            "http2=True needs the reactor ('native'/'native-reactor') or "
            "the Python orchestration paths"
        )
    return engine, inner


def executor_mode(fetch_executor: str) -> str:
    """Requested pool dispatch shape for a ``fetch_executor`` config value:
    "native" prefers the reactor (the post-BENCH_r05 default — the epoll
    loop + SPSC-ring handoff), "native-reactor"/"native-threads" pin it
    explicitly. What actually engaged is ``NativeFetchPool.mode`` (only a
    stale .so build without the reactor symbols still falls back to the
    thread pool — TLS runs the reactor's nonblocking state machine)."""
    return "threads" if fetch_executor == "native-threads" else "reactor"


#: Process-wide count of honest reactor→legacy fallbacks (satellite:
#: a TLS user must not benchmark the wrong executor without noticing).
_fallback_count = 0


def executor_fallbacks() -> int:
    """How many requested-reactor runs fell back to the legacy pool in
    this process (preflight surfaces this next to the engine row)."""
    return _fallback_count


def check_executor_engaged(pool, fetch_executor: str) -> int:
    """Honest-fallback contract for a freshly created pool.

    ``native`` PREFERS the reactor but may legitimately run legacy (stale
    .so): that emits ONE counted warning line, never silence. Explicitly
    pinned ``native-reactor`` that cannot engage the reactor is a hard
    error — a pinned A/B arm must fail loudly, not mislabel itself.
    Returns 1 when a fallback was recorded, else 0.
    """
    global _fallback_count
    requested = executor_mode(fetch_executor)
    if pool.mode == requested:
        return 0
    if fetch_executor == "native-reactor":
        pool.close()
        raise RuntimeError(
            "fetch_executor='native-reactor' was pinned but the engine "
            f"engaged '{pool.mode}' (stale libtpubench.so without the "
            "reactor symbols, or reactor creation failed) — refusing the "
            "silent downgrade"
        )
    warn_fallback(requested, pool.mode, f"fetch_executor={fetch_executor!r}")
    return 1


def warn_fallback(requested: str, engaged: str, why: str = "") -> None:
    """The one-line counted fallback warning (shared by run_read and
    preflight): every honest reactor→legacy downgrade prints exactly one
    stderr line carrying the running process-wide count."""
    global _fallback_count
    _fallback_count += 1
    import sys

    tail = f"; {why}" if why else ""
    print(
        f"tpubench: warning: fetch executor fell back to '{engaged}' "
        f"(requested '{requested}'{tail}; fallback #{_fallback_count} "
        "this process)",
        file=sys.stderr,
    )


def _reactor_loops() -> int:
    """Event-loop thread count for reactor pools: one loop per ~2 usable
    cores, capped small — on the share-capped 1-core hosts BENCH_r05 ran
    on, ONE loop (plus the draining consumer) is exactly the shape that
    beats 48 pthreads fighting over the core."""
    import os

    cores = (
        len(os.sched_getaffinity(0))
        if hasattr(os, "sched_getaffinity")
        else os.cpu_count() or 1
    )
    return max(1, min(4, cores // 2))


def _make_pool(engine, inner, threads: int, cap: int, mode: str = "reactor"):
    """Executor pool matching the backend's endpoint transport: TLS from
    the endpoint scheme, h2 multiplexing when the transport asked for
    http2 (ALPN on TLS, prior-knowledge h2c on plaintext — reactor
    only)."""
    t = inner.transport
    return engine.pool_create(
        threads=threads,
        cap=cap,
        tls=inner.scheme == "https",
        cafile=t.tls_ca_file,
        insecure=t.tls_insecure_skip_verify,
        mode=mode,
        loops=_reactor_loops(),
        h2=bool(t.http2) and mode == "reactor",
    )


def _stamp_native_delta(res: RunResult, engine, stats0: dict) -> None:
    """tb_stats delta across the run (read.py parity): makes the wire
    counters AND the completion-batching ratio (pool_completions /
    pool_wakes > 1 = batching engaged) visible in the result JSON."""
    delta = {k: v - stats0.get(k, 0) for k, v in engine.stats().items()}
    if any(delta.values()):
        res.extra["native_transport"] = delta


def _wake_batch_stats(batches: list) -> Optional[dict]:
    """Per-wake completion batch sizes → the distribution the reactor
    acceptance gates on (completions-per-wake p50 > 8 at high fan-out vs
    ~1 on the legacy per-completion handoff)."""
    import statistics

    if not batches:
        return None
    return {
        "wakes": len(batches),
        "p50": statistics.median(batches),
        "max": max(batches),
        "mean": round(sum(batches) / len(batches), 3),
    }


def run_read_native_executor(cfg: BenchConfig, backend: StorageBackend) -> RunResult:
    """Fetch fan-out on the executor, bytes discarded in host RAM
    (reference parity: ``io.Discard``, main.go:140). Client retry policy
    applies to completions (see module docstring); the executor's one
    stale-connection retransmit remains underneath as pool hygiene, exactly
    like the Python path's NativeConnPool."""
    from tpubench.native.engine import PERMANENT_CODES

    engine, inner = _require_native_http(cfg, backend)
    w = cfg.workload
    if cfg.staging.mode != "none":
        raise ValueError(
            "run_read_native_executor is the staging='none' runner; staged "
            "ingest uses run_read_native_staged"
        )

    names = [f"{w.object_name_prefix}{i}" for i in range(w.workers)]
    # One stat per object OUTSIDE the timed window: discard mode counts
    # whatever the server streams, so without an expected size a
    # misrouted 200 (error page, stale object) would silently inflate
    # bytes_total and the headline GB/s.
    sizes = {n: inner.stat(n).size for n in set(names)}
    metrics = MetricSet()
    recorders = [metrics.new_worker(f"w{i}") for i in range(w.workers)]
    reads_per = w.read_calls_per_worker
    total_reads = w.workers * reads_per
    if total_reads <= 0:
        res = RunResult(workload="read", config=cfg.to_dict(), summaries={})
        res.extra["fetch_executor"] = w.fetch_executor
        return res
    pool = _make_pool(engine, inner, w.workers, max(4, 2 * w.workers),
                      mode=executor_mode(w.fetch_executor))
    fellback = check_executor_engaged(pool, w.fetch_executor)
    native_stats0 = engine.stats()
    retry = RetryScheduler(cfg.transport.retry)
    bytes_total = 0
    errors = 0
    first_error = ""
    wake_batches: list = []

    # Discard mode (NULL buffer): pool workers stream each body through a
    # per-thread hot granule-sized scratch and drop it — exact io.Discard
    # parity with the reference hot loop (main.go:140) and the Python
    # staging-"none" path. Landing whole bodies would charge this config
    # DRAM-write bandwidth the comparison paths never pay (measured ~25%
    # on the single-core host). The tag encodes the worker:
    # tag = wid * reads_per + seq.
    def submit(wid: int, seq: int) -> None:
        host, port, path, headers = inner.native_request_parts(names[wid])
        pool.submit_to(
            host, port, path, 0, 0, headers=headers,
            tag=wid * reads_per + seq,
        )

    def resubmit(tag: int) -> None:
        host, port, path, headers = inner.native_request_parts(
            names[tag // reads_per]
        )
        pool.submit_to(host, port, path, 0, 0, headers=headers, tag=tag)

    from tpubench.obs.exporters import metrics_session_from_config

    session = metrics_session_from_config(
        cfg, metrics, bytes_fn=lambda: bytes_total
    )
    controller = None
    metrics.ingest.start()
    try:
        if session is not None:
            session.__enter__()
        # One outstanding read per logical worker, admitted through a
        # LIVE fan-out cap — the serial per-worker loop's concurrency
        # shape, with the cap itself a tune-controller knob. Workers
        # with remaining reads and no read in flight sit in `runnable`;
        # the pump admits them while outstanding < active. active ==
        # w.workers (the default, tuning off) reproduces the old
        # complete-one-refill-same-worker behavior; a shrink drains
        # naturally (completions stop being refilled past the cap, and
        # NO work is lost — the total read count still completes, just
        # at the lower concurrency). A read awaiting a retry backoff
        # keeps its in-flight slot, so its worker stays serialized.
        from collections import deque

        active = [w.workers]  # mutable cell: the tune workers actuator
        per_worker_next = [0] * w.workers
        runnable = deque(range(w.workers))
        outstanding = 0
        completed = 0

        def pump() -> None:
            nonlocal outstanding
            while outstanding < active[0] and runnable:
                wid = runnable.popleft()
                submit(wid, per_worker_next[wid])
                per_worker_next[wid] += 1
                outstanding += 1

        if getattr(cfg, "tune", None) is not None and cfg.tune.enabled:
            from tpubench.tune.controller import (
                Knob,
                RecorderSampler,
                TuneController,
            )

            knobs = []
            if "workers" in set(cfg.tune.knobs) and w.workers > 1:
                knobs.append(Knob(
                    "workers", w.workers,
                    lambda v: active.__setitem__(0, int(v)),
                    lo=1, hi=w.workers, mode="mul",
                ))
            if knobs:
                controller = TuneController(
                    cfg.tune, knobs,
                    RecorderSampler(
                        [r for r, _ in recorders], lambda: bytes_total
                    ),
                )
                controller.start()

        pump()

        def handle(c: dict) -> None:
            nonlocal completed, errors, first_error, bytes_total, outstanding
            tag = c["tag"]
            wid = tag // reads_per
            read_rec, fb_rec = recorders[wid]
            verdict = _classify(c["result"], c["status"], PERMANENT_CODES)
            if verdict == "ok" and c["result"] != sizes[names[wid]]:
                # Discard mode counts whatever arrived: a 200 whose byte
                # count disagrees with the object's stat size is a
                # server-side misroute/staleness, not a success.
                verdict = "transient"
            if verdict != "ok":
                pause = retry.offer(tag, verdict)
                if pause is not None:
                    retry.push(tag, tag, pause)
                    return  # slot for this read stays inflight
                retry.done(tag)
                errors += 1
                if not first_error:
                    first_error = (
                        f"worker {wid}: result {c['result']} "
                        f"status {c['status']}"
                    )
            else:
                retry.done(tag)
                read_rec.record_ns(c["total_ns"])
                if c["first_byte_ns"]:
                    fb_rec.record_ns(c["first_byte_ns"] - c["start_ns"])
                bytes_total += c["result"]
            completed += 1
            outstanding -= 1
            if verdict != "ok" and w.abort_on_error:
                # errgroup semantics (main.go:200-219): first (post-retry)
                # error cancels the run — same contract as the Python path.
                raise RuntimeError(
                    f"native fetch executor: read failed ({first_error})"
                )
            if per_worker_next[wid] < reads_per:
                runnable.append(wid)

        # With tuning live, the wait must wake often enough to apply a
        # fan-out GROW promptly even when the shrunken pool completes
        # slowly; the stall guard is wall-clock-based (120 s without a
        # completion) so shorter waits don't change its meaning.
        wait_cap_ms = 100 if controller is not None else 30_000
        last_completion = time.monotonic()
        while completed < total_reads:
            for tag in retry.pop_due():
                resubmit(tag)
            pump()
            # Batched drain (tb_pool_next_batch): under fan-out the
            # workers land completions faster than Python processes them
            # — one wake takes the whole backlog in a single native lock
            # crossing instead of paying the handoff per completion (the
            # BENCH_r05 deficit attribution).
            cs = pool.next_batch(timeout_ms=retry.next_due_in_ms(wait_cap_ms))
            if not cs:
                if retry.waiting:
                    continue  # timeout was just a backoff pause elapsing
                if time.monotonic() - last_completion > 120:
                    raise RuntimeError("native fetch executor stalled (120s)")
                continue
            last_completion = time.monotonic()
            wake_batches.append(len(cs))
            for c in cs:
                handle(c)
    finally:
        tune_stats = controller.stop() if controller is not None else None
        # Stop the clock BEFORE teardown (thread joins + multi-MB munmaps
        # must not bias the measured window vs the Python path).
        metrics.ingest.stop()
        metrics.ingest.bytes = bytes_total
        if session is not None:
            session.__exit__(None, None, None)  # guaranteed final flush
        pool.close()

    wall = metrics.ingest.seconds
    res = RunResult(
        workload="read",
        config=cfg.to_dict(),
        bytes_total=bytes_total,
        wall_seconds=wall,
        gbps=metrics.ingest.gbps(),
        gbps_per_chip=metrics.ingest.gbps(),
        n_chips=1,
        summaries=metrics.summaries(),
        errors=errors,
    )
    res.extra["fetch_executor"] = w.fetch_executor
    res.extra["executor_mode"] = pool.mode
    if fellback:
        res.extra["executor_fallback"] = True
    res.extra["executor_threads"] = w.workers
    bs = _wake_batch_stats(wake_batches)
    if bs is not None:
        res.extra["completions_per_wake"] = bs
    _stamp_native_delta(res, engine, native_stats0)
    res.extra["client_retry"] = (
        f"gax policy over completions (policy={cfg.transport.retry.policy}, "
        f"retries={retry.retries})"
    )
    res.extra["retries"] = retry.retries
    if tune_stats is not None:
        res.extra["tune"] = tune_stats
    if session is not None:
        res.extra["metrics_export"] = session.summary()
    if first_error:
        res.extra["first_error"] = first_error
    return res


class _SlotPipeline:
    """Per-worker staging ring fed by the executor: ``depth`` native slots
    cycle through FREE → FETCHING (a range GET lands in the slot's buffer)
    → TRANSFER (async ``jax.device_put``) → FREE. The companion of
    ``DevicePutStager`` for executor-driven fetch; same accounting surface
    (stage histogram = submit→transfer-complete per slot, staged bytes,
    transfer count, optional on-device checksum)."""

    def __init__(self, worker_id: int, engine, slot_bytes: int, depth: int,
                 lane: int, device, validate: bool):
        import jax
        import jax.numpy as jnp

        from tpubench.metrics.recorder import LatencyRecorder

        self.device = device
        self._jax = jax
        self._slot_bytes = slot_bytes
        self.bufs = [engine.alloc(slot_bytes) for _ in range(depth)]
        self.arrays = [b.as_2d(lane) for b in self.bufs]
        self.free = list(range(depth))
        self.stage_recorder = LatencyRecorder(f"w{worker_id}/stage")
        self.staged_bytes = 0
        self.transfers = 0
        self._validate = validate
        self._host_sum = 0
        self._dev_sum = (
            jax.device_put(jnp.zeros((), jnp.uint32), device) if validate else None
        )

    def launch(self, slot: int, nbytes: int):
        """Async device_put of the slot; returns the in-flight future.
        Partial slots (object tail) zero-pad so checksums and landed
        shapes see only real bytes — steady-state full slots skip the
        memset."""
        import numpy as np

        arr = self.arrays[slot]
        if nbytes < self._slot_bytes:
            self.bufs[slot].array[nbytes:] = 0
        if self._validate:
            chunk = self.bufs[slot].array[:nbytes]
            self._host_sum += int(chunk.sum(dtype=np.uint64))
        submit_ns = time.perf_counter_ns()
        fut = self._jax.device_put(arr, self.device)
        self.transfers += 1
        if self._validate:
            from tpubench.staging.device import _accum_checksum

            # Validation trades overlap for integrity (same contract as
            # DevicePutStager): the accumulate must read the landed array
            # before the slot can be reused, so complete it now.
            self._dev_sum = _accum_checksum(self._dev_sum, fut)
            self._dev_sum.block_until_ready()
        return fut, submit_ns, nbytes

    def complete(self, slot: int, submit_ns: int, nbytes: int) -> None:
        self.stage_recorder.record_ns(time.perf_counter_ns() - submit_ns)
        self.staged_bytes += nbytes
        self.free.append(slot)

    def checksum(self) -> Optional[bool]:
        if not self._validate:
            return None
        dev = int(self._jax.device_get(self._dev_sum))
        return dev == self._host_sum % (2**32)

    def close(self) -> None:
        for b in self.bufs:
            b.free()
        self.bufs = []
        self.arrays = []


def run_read_native_staged(cfg: BenchConfig, backend: StorageBackend) -> RunResult:
    """The flagship staged ingest with NO Python in the fetch hot loop.

    Each worker's object is read as a sequence of slot-sized byte ranges
    (``Range: bytes=a-b`` — the fake server and GCS JSON media GETs both
    honor it); every range is one executor task landing bytes directly in
    a staging slot's native buffer. On completion Python issues the one
    async ``jax.device_put`` for that slot and immediately resubmits the
    next range into a free slot — fetch (C++ pthreads) and host→HBM
    transfers overlap continuously, bounded by ``staging.depth`` slots per
    worker. Reads of one worker stay sequential (the reference's serial
    per-worker loop, main.go:127-153); ranges WITHIN a read fetch
    concurrently.
    """
    import jax

    from tpubench.config import MB
    from tpubench.native.engine import PERMANENT_CODES

    engine, inner = _require_native_http(cfg, backend)
    w = cfg.workload
    s = cfg.staging
    if s.mode != "device_put":
        raise ValueError(
            "fetch_executor='native' staged ingest supports staging "
            "'device_put' (pallas staging rides the Python orchestration "
            "paths)"
        )
    lane = s.lane
    # The pipeline needs >= 2 slots per worker for fetch/transfer overlap
    # (one slot would serialize them); config depth sets the ceiling.
    depth = max(2, s.depth)
    # Slot size under the host budget. Unlike budgeted_slot_bytes there is
    # NO granule floor: this path has no granule buffer — the slot IS the
    # fetch unit (one range GET per slot), so any lane multiple is legal.
    budget = max(1, s.host_budget_mb) * MB
    per_worker = budget // max(1, w.workers * depth)
    slot_bytes = max(lane, min(s.slot_bytes, per_worker))
    slot_bytes = (slot_bytes + lane - 1) // lane * lane

    names = [f"{w.object_name_prefix}{i}" for i in range(w.workers)]
    sizes = [inner.stat(n).size for n in names]
    reads_per = w.read_calls_per_worker
    total_reads = w.workers * reads_per
    metrics = MetricSet()
    recorders = [metrics.new_worker(f"w{i}") for i in range(w.workers)]
    if total_reads <= 0 or sum(sizes) == 0:
        res = RunResult(workload="read", config=cfg.to_dict(), summaries={})
        res.extra["fetch_executor"] = w.fetch_executor
        return res

    devices = jax.local_devices()
    pipes = [
        _SlotPipeline(
            i, engine, slot_bytes, depth, lane,
            devices[i % len(devices)], s.validate_checksum,
        )
        for i in range(w.workers)
    ]

    # Per-worker read-progress state machine.
    class _W:
        __slots__ = ("call", "next_off", "ranges_out", "t0", "first_fb", "failed")

    ws = []
    completed_upfront = 0
    for i in range(w.workers):
        st = _W()
        st.call = 0          # current read-call index
        st.next_off = 0      # next unsubmitted byte offset of this call
        st.ranges_out = 0    # in-flight (or retrying) ranges of this call
        st.t0 = 0            # perf_counter_ns at first submit of this call
        st.first_fb = False  # first-byte recorded for this call
        st.failed = False    # this call had a post-retry range failure
        if sizes[i] == 0:
            # Zero-length object: every read completes trivially (nothing
            # to range-shard); without this the state machine would never
            # see a completion for this worker.
            st.call = reads_per
            st.next_off = 0
            completed_upfront += reads_per
        ws.append(st)

    pool = _make_pool(engine, inner, w.workers, max(8, 2 * w.workers * depth),
                      mode=executor_mode(w.fetch_executor))
    fellback = check_executor_engaged(pool, w.fetch_executor)
    native_stats0 = engine.stats()
    retry = RetryScheduler(cfg.transport.retry)
    wake_batches: list = []
    inflight: dict[int, tuple] = {}  # tag -> (wid, slot, start, length)
    # PER-WORKER transfer FIFOs: completion order is FIFO per device, not
    # globally (workers round-robin across devices) — one global queue
    # would head-of-line-block every worker behind one slow device_put.
    transfers: list[list] = [[] for _ in range(w.workers)]
    transfers_n = 0
    next_tag = 0
    bytes_total = 0
    errors = 0
    first_error = ""
    completed_reads = completed_upfront

    def submit_range(wid: int) -> None:
        nonlocal next_tag
        st = ws[wid]
        pipe = pipes[wid]
        slot = pipe.free.pop()
        start = st.next_off
        length = min(slot_bytes, sizes[wid] - start)
        if st.next_off == 0 and st.ranges_out == 0:
            st.t0 = time.perf_counter_ns()
            st.first_fb = False
        st.next_off += length
        st.ranges_out += 1
        host, port, path, headers = inner.native_request_parts(names[wid])
        headers += f"Range: bytes={start}-{start + length - 1}\r\n"
        tag = next_tag
        next_tag += 1
        pool.submit_to(
            host, port, path, pipe.bufs[slot].address, length,
            headers=headers, tag=tag,
        )
        inflight[tag] = (wid, slot, start, length)

    def resubmit(tag: int) -> None:
        wid, slot, start, length = inflight[tag]
        # Headers rebuilt per attempt — native_request_parts keeps bearer
        # tokens fresh across backoff windows (same as the unstaged runner
        # and the Python path).
        host, port, path, headers = inner.native_request_parts(names[wid])
        headers += f"Range: bytes={start}-{start + length - 1}\r\n"
        pool.submit_to(
            host, port, path, pipes[wid].bufs[slot].address, length,
            headers=headers, tag=tag,
        )

    def drain_ready_transfers() -> None:
        # jax.Array.is_ready() is the non-blocking completion probe; a JAX
        # build without it degrades to inline (blocking) drains — never to
        # freeing a slot whose transfer might still be reading it.
        nonlocal transfers_n
        for wid in range(w.workers):
            q = transfers[wid]
            while q:
                fut = q[0][1]
                if hasattr(fut, "is_ready"):
                    if not fut.is_ready():
                        break
                else:
                    fut.block_until_ready()
                slot, _, submit_ns, nbytes = q.pop(0)
                pipes[wid].complete(slot, submit_ns, nbytes)
                transfers_n -= 1

    def drain_one_transfer_blocking() -> None:
        # Block on the OLDEST in-flight transfer across workers (per-queue
        # heads only — within a worker completion is FIFO).
        nonlocal transfers_n
        wid = min(
            (i for i in range(w.workers) if transfers[i]),
            key=lambda i: transfers[i][0][2],
        )
        slot, fut, submit_ns, nbytes = transfers[wid].pop(0)
        fut.block_until_ready()
        pipes[wid].complete(slot, submit_ns, nbytes)
        transfers_n -= 1

    def can_submit(wid: int) -> bool:
        st = ws[wid]
        if st.call >= reads_per or not pipes[wid].free:
            return False
        if st.next_off < sizes[wid]:
            return True
        # Current call fully submitted; the next call may start only when
        # this one's fetches all settled (serial reads per worker).
        return False

    def _handle_staged_completion(c: dict) -> None:
        nonlocal bytes_total, errors, first_error, completed_reads, transfers_n
        tag = c["tag"]
        wid, slot, start, length = inflight[tag][:4]
        st = ws[wid]
        pipe = pipes[wid]
        verdict = _classify(c["result"], c["status"], PERMANENT_CODES)
        if verdict == "ok" and c["result"] != length:
            # Range honored means exactly `length` bytes; anything else
            # is a protocol-shape failure (server ignored the range).
            verdict = "permanent"
        if verdict != "ok":
            pause = retry.offer(tag, verdict)
            if pause is not None:
                retry.push(tag, tag, pause)
                return  # slot stays owned by the retrying task
            if not st.failed:
                # One error per failed READ (not per failed range) —
                # RunResult.errors parity with the other paths.
                errors += 1
            if not first_error:
                first_error = (
                    f"worker {wid} range {start}+{length}: "
                    f"result {c['result']} status {c['status']}"
                )
            del inflight[tag]
            retry.done(tag)
            pipe.free.append(slot)
            # Abandon this call: stop submitting its ranges; it
            # completes (as a failed read) when in-flight ones settle.
            st.next_off = sizes[wid]
            st.failed = True
            st.ranges_out -= 1
            if w.abort_on_error:
                raise RuntimeError(
                    f"staged executor: read failed ({first_error})"
                )
        else:
            retry.done(tag)
            del inflight[tag]
            if not st.first_fb and c["first_byte_ns"]:
                recorders[wid][1].record_ns(
                    c["first_byte_ns"] - c["start_ns"]
                )
                st.first_fb = True
            bytes_total += length
            st.ranges_out -= 1
            transfers[wid].append((slot,) + pipe.launch(slot, length))
            transfers_n += 1
        # Call complete when fully submitted and nothing outstanding.
        if st.next_off >= sizes[wid] and st.ranges_out == 0:
            if not st.failed:
                # Failed reads are counted in `errors`, not in the
                # latency histogram (Python-path parity).
                recorders[wid][0].record_ns(time.perf_counter_ns() - st.t0)
            completed_reads += 1
            st.call += 1
            st.next_off = 0 if st.call < reads_per else sizes[wid]
            st.failed = False

    from tpubench.obs.exporters import metrics_session_from_config

    session = metrics_session_from_config(
        cfg, metrics, bytes_fn=lambda: bytes_total
    )
    metrics.ingest.start()
    last_progress = time.monotonic()
    try:
        if session is not None:
            session.__enter__()
        while completed_reads < total_reads:
            if inflight and time.monotonic() - last_progress > 120:
                # Same wedged-completion-queue guard as the unstaged
                # runner: fail loudly instead of polling forever.
                raise RuntimeError("staged executor stalled (120s)")
            for tag in retry.pop_due():
                resubmit(tag)
            drain_ready_transfers()
            for wid in range(w.workers):
                while can_submit(wid):
                    submit_range(wid)
            if not inflight and not retry.waiting:
                if transfers_n:
                    drain_one_transfer_blocking()
                    continue
                # Nothing in flight anywhere but reads remain — every
                # remaining call must be startable; loop submits them.
                if any(can_submit(i) for i in range(w.workers)):
                    continue
                raise RuntimeError("staged executor: no runnable work left")
            # In-flight transfers drain via is_ready() polls at the top of
            # the loop: keep the wait short while any are pending so the
            # device-side pipeline is never starved behind a slow fetch.
            cap_ms = 5 if transfers_n else 100
            # Batched drain: one native lock crossing takes the whole
            # completion backlog (per-worker slot launches then happen
            # back-to-back without re-paying the handoff per range).
            cs = pool.next_batch(timeout_ms=retry.next_due_in_ms(cap_ms))
            if not cs:
                continue
            last_progress = time.monotonic()
            wake_batches.append(len(cs))
            for c in cs:
                _handle_staged_completion(c)
        # All fetches done; drain remaining transfers into the timed window
        # (staged bandwidth counts transfer completion, same as the Python
        # staged path's finish()).
        while transfers_n:
            drain_one_transfer_blocking()
    finally:
        metrics.ingest.stop()
        metrics.ingest.bytes = bytes_total
        for pipe in pipes:
            metrics.stage_latency.append(pipe.stage_recorder)
        if session is not None:
            session.__exit__(None, None, None)
        # Error/interrupt exits: the slot buffers may still be read by
        # in-flight fetches (pool pthreads) AND in-flight device_put
        # transfers (plain numpy views do not pin). Settle BOTH before any
        # free — the same drain-before-free contract as
        # DevicePutStager.finish().
        pool.close()  # joins workers after queued tasks finish their writes
        for q in transfers:
            for _, fut, _, _ in q:
                try:
                    fut.block_until_ready()
                except Exception:
                    pass  # a failed transfer settles; freeing is now safe
            q.clear()
        for pipe in pipes:
            pipe.close()

    wall = metrics.ingest.seconds
    n_chips = len(devices)
    staged = sum(p.staged_bytes for p in pipes)
    gbps = metrics.ingest.gbps()
    res = RunResult(
        workload="read",
        config=cfg.to_dict(),
        bytes_total=bytes_total,
        wall_seconds=wall,
        gbps=gbps,
        gbps_per_chip=gbps / max(1, n_chips),
        n_chips=n_chips,
        summaries=metrics.summaries(),
        errors=errors,
    )
    res.extra["fetch_executor"] = w.fetch_executor
    res.extra["executor_mode"] = pool.mode
    if fellback:
        res.extra["executor_fallback"] = True
    res.extra["executor_threads"] = w.workers
    bs = _wake_batch_stats(wake_batches)
    if bs is not None:
        res.extra["completions_per_wake"] = bs
    _stamp_native_delta(res, engine, native_stats0)
    res.extra["staging_zero_copy"] = True
    res.extra["staged_bytes"] = staged
    res.extra["staged_gbps"] = (staged / 1e9) / wall if wall > 0 else 0.0
    res.extra["staged_gbps_per_chip"] = res.extra["staged_gbps"] / max(1, n_chips)
    res.extra["slot_bytes"] = slot_bytes
    res.extra["depth"] = depth
    res.extra["retries"] = retry.retries
    res.extra["client_retry"] = (
        f"gax policy over completions (policy={cfg.transport.retry.policy}, "
        f"retries={retry.retries})"
    )
    checks = [p.checksum() for p in pipes]
    if s.validate_checksum:
        res.extra["checksum_ok"] = all(c is True for c in checks)
    if session is not None:
        res.extra["metrics_export"] = session.summary()
    if first_error:
        res.extra["first_error"] = first_error
    return res
