"""Filesystem-path workloads (reference ``benchmark-script/``, SURVEY §2.2).

Five drivers sharing the reference's skeleton — flags → open files indexed
by worker id → fan-out → join — implemented over the native engine's timed
block I/O (per-thread latency arrays; the GIL is released inside every
native call so threads get real I/O concurrency). Reference bugs
deliberately not reproduced (SURVEY §7 list): re-read-at-EOF, racy shared
latency slice, dead listing impl, unsynchronized offset shuffle.

Drivers:

* :func:`run_read_fs`     — #11 sequential read (read_operation/main.go)
* :func:`run_write`       — #12 durable write  (write_operations/main.go)
* :func:`run_listing`     — #13 list           (list_operation/main.go)
* :func:`run_open_file`   — #14 open/FD-hold   (open_file/main.go)
* :func:`run_ssd_compare` — #15 percentile     (ssd_test/main.go)
"""

from __future__ import annotations

import contextlib
import os
import subprocess
import threading
import time
from typing import Optional

import numpy as np

from tpubench.config import BenchConfig
from tpubench.metrics.percentiles import summarize_ns
from tpubench.metrics.report import RunResult
from tpubench.native import get_engine
from tpubench.storage.base import deterministic_bytes
from tpubench.workloads.common import WorkerGroup

KB = 1024


def _engine_or_raise():
    e = get_engine()
    if e is None:
        raise RuntimeError("native engine unavailable (g++ build failed)")
    return e


# ------------------------------------------------------- mount orchestration
def _run_hook(template: str, dirpath: str) -> None:
    cmd = template.format(dir=dirpath)
    proc = subprocess.run(cmd, shell=True, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"mount hook failed ({proc.returncode}): {cmd}\n{proc.stderr[-500:]}"
        )


# Dirs mounted by the CURRENT maybe_mounted bracket and not yet touched by
# a workload: a fresh mount is already cold, so the cold-round _remount can
# skip one full unmount+mount cycle (gcsfuse mounts cost seconds each).
_fresh_mounts: set[str] = set()
_fresh_lock = threading.Lock()


@contextlib.contextmanager
def maybe_mounted(cfg: BenchConfig):
    """Bracket a run with the configured mount/unmount commands — the
    launcher convention every benchmark-script reproduces
    (read_operations.sh:18-21: mount gcsfuse with explicit cache TTLs, run,
    unmount). Empty commands = pre-mounted dir (the default). Mount failure
    aborts (the bench would measure the wrong filesystem); unmount failure
    only warns."""
    w = cfg.workload
    if w.mount_cmd:
        _run_hook(w.mount_cmd, w.dir)
        with _fresh_lock:
            _fresh_mounts.add(w.dir)
    try:
        yield
    finally:
        with _fresh_lock:
            _fresh_mounts.discard(w.dir)
        if w.unmount_cmd:
            try:
                _run_hook(w.unmount_cmd, w.dir)
            except RuntimeError as e:
                import warnings

                warnings.warn(str(e), stacklevel=2)


def _remount(cfg: BenchConfig) -> bool:
    """True cold-cache point: unmount + mount when both hooks are
    configured (list_operations.sh runs its cold variant against a fresh
    mount with zero cache TTLs). A mount that maybe_mounted just performed
    is already cold — consumed without paying another cycle. Returns
    whether the cold state came from a (re)mount."""
    w = cfg.workload
    with _fresh_lock:
        if w.dir in _fresh_mounts:
            # A fresh mount is cold whether or not an unmount hook exists
            # (mount-only config: the dir was pre-unmounted).
            _fresh_mounts.discard(w.dir)  # one cold round per fresh mount
            return True
    if not (w.mount_cmd and w.unmount_cmd):
        return False
    _run_hook(w.unmount_cmd, w.dir)
    _run_hook(w.mount_cmd, w.dir)
    return True


def prepare_files(
    dirpath: str, count: int, size: int, name_fmt: str = "file_{i}"
) -> list[str]:
    """Create the worker-indexed data files the reference expects on the
    mount (worker i owns file_<i>, read_operation/main.go:33)."""
    os.makedirs(dirpath, exist_ok=True)
    paths = []
    for i in range(count):
        name = name_fmt.format(i=i)
        p = os.path.join(dirpath, name)
        if not (os.path.exists(p) and os.path.getsize(p) == size):
            data = deterministic_bytes(name, size)
            with open(p, "wb") as f:
                f.write(data.tobytes())
    # fsync directory once so benchmarks start from a durable state
        paths.append(p)
    return paths


# ------------------------------------------------------------------- #11 --
def run_read_fs(cfg: BenchConfig, direct: bool = True) -> RunResult:
    """Sequential read: each thread streams its file ``read_count`` times
    through a ``block_size`` buffer. Repeat passes re-read from offset 0
    (defined semantics; the reference accidentally read at EOF after pass 1,
    read_operation/main.go:46)."""
    w = cfg.workload
    eng = _engine_or_raise()
    n = w.threads
    block = w.block_size_kb * KB
    pass_lats: list[np.ndarray] = [np.empty(0)] * n
    totals = [0] * n
    directs = [False] * n

    def worker(i: int, cancel) -> None:
        path = os.path.join(w.dir, f"file_{i}")
        fd, applied = eng.open(path, direct=direct)
        directs[i] = applied
        buf = eng.alloc(block)
        try:
            total, lats = eng.read_file_seq(fd, buf, passes=w.read_count)
            totals[i] = total
            pass_lats[i] = lats
        finally:
            eng.close(fd)
            buf.free()

    t0 = time.perf_counter()
    WorkerGroup(abort_on_error=w.abort_on_error).run(n, worker, name="read_fs")
    wall = time.perf_counter() - t0

    merged = np.concatenate([a for a in pass_lats if a.size]) if n else np.empty(0)
    total_bytes = sum(totals)
    res = RunResult(
        workload="read_fs",
        config=cfg.to_dict(),
        bytes_total=total_bytes,
        wall_seconds=wall,
        gbps=(total_bytes / 1e9) / wall if wall > 0 else 0.0,
        summaries={"pass": summarize_ns(merged)} if merged.size else {},
    )
    res.extra["o_direct"] = all(directs)
    return res


# ------------------------------------------------------------------- #12 --
def run_write(cfg: BenchConfig, direct: bool = True) -> RunResult:
    """Durable write: per block pwrite + (default) fsync — the reference
    fsyncs EVERY block (write_operations/main.go:63-71), making this a
    durability-latency bench, not a throughput bench. Block latencies
    include the fsync. ``write_count`` repeats overwrite the same file
    (O_TRUNC reopen each round, :36)."""
    w = cfg.workload
    eng = _engine_or_raise()
    os.makedirs(w.dir, exist_ok=True)
    n = w.threads
    block = w.block_size_kb * KB
    fsize = w.file_size_mb * 1024 * KB
    n_blocks = max(1, fsize // block)
    offsets = np.arange(n_blocks, dtype=np.int64) * block
    lat_all: list[np.ndarray] = [np.empty(0)] * n
    totals = [0] * n

    def worker(i: int, cancel) -> None:
        path = os.path.join(w.dir, f"file_{i}")
        buf = eng.alloc(block)
        eng.fill_random(buf, seed=w.seed + i + 1)
        lats = []
        try:
            for _ in range(w.write_count):
                if cancel.is_set():
                    break
                fd, _ = eng.open(path, write=True, create=True, direct=direct)
                try:
                    total, lat = eng.pwrite_blocks(
                        fd, buf, block, offsets, fsync_each=w.fsync_every_block
                    )
                    totals[i] += total
                    lats.append(lat)
                finally:
                    eng.close(fd)
        finally:
            buf.free()
        if lats:
            lat_all[i] = np.concatenate(lats)

    t0 = time.perf_counter()
    WorkerGroup(abort_on_error=w.abort_on_error).run(n, worker, name="write")
    wall = time.perf_counter() - t0

    merged = np.concatenate([a for a in lat_all if a.size])
    total_bytes = sum(totals)
    res = RunResult(
        workload="write",
        config=cfg.to_dict(),
        bytes_total=total_bytes,
        wall_seconds=wall,
        gbps=(total_bytes / 1e9) / wall if wall > 0 else 0.0,
        summaries={"block_write": summarize_ns(merged)} if merged.size else {},
    )
    res.extra["fsync_every_block"] = w.fsync_every_block
    return res


# ------------------------------------------------------------------- #13 --
def run_listing(cfg: BenchConfig, rounds: Optional[int] = None) -> RunResult:
    """List + per-entry stat — the semantics of the reference's (dead)
    in-process impl (list_operation/main.go:14-36), which we make the live
    one (the shipped ``ls -lah`` subprocess variant, :41-66, measures mostly
    process spawn and is not reproduced).

    Hot/cold (list_operations.sh:11-21 runs one hot-cache and one cold-cache
    variant): round 0 here is the COLD round — preceded by a remount when
    mount hooks are configured (a true cold cache), otherwise simply the
    first touch — and the remaining rounds are HOT (caches warmed by round
    0). Both summaries are reported separately plus combined."""
    w = cfg.workload
    rounds = rounds if rounds is not None else w.list_rounds
    rounds = max(1, rounds)
    remounted = _remount(cfg)
    lat = []
    entries = 0
    t0 = time.perf_counter()
    for _ in range(rounds):
        t = time.perf_counter_ns()
        with os.scandir(w.dir) as it:
            entries = sum(1 for e in it if e.stat() is not None)
        lat.append(time.perf_counter_ns() - t)
    wall = time.perf_counter() - t0
    summaries = {"list": summarize_ns(np.array(lat))}
    summaries["list_cold"] = summarize_ns(np.array(lat[:1]))
    if len(lat) > 1:
        summaries["list_hot"] = summarize_ns(np.array(lat[1:]))
    res = RunResult(
        workload="listing",
        config=cfg.to_dict(),
        wall_seconds=wall,
        summaries=summaries,
    )
    res.extra["entries"] = entries
    res.extra["rounds"] = rounds
    res.extra["cold_via_remount"] = remounted
    return res


# ------------------------------------------------------------------- #14 --
def run_open_file(cfg: BenchConfig, direct: bool = True) -> RunResult:
    """Open N files, hold the FDs ``hold_seconds`` (reference holds 3 min so
    gcsfuse memory can be observed, open_file/main.go:52-55), close.
    Per-open latency is the metric.

    Hot/cold (open_file_operation.sh:10-19 runs hot- and cold-stat-cache
    variants): a COLD pass (after a remount when mount hooks are
    configured) then a HOT pass; the FD hold applies to the hot pass."""
    w = cfg.workload
    eng = _engine_or_raise()

    def open_pass():
        lat, fds = [], []
        try:
            for i in range(w.open_files):
                path = os.path.join(w.dir, f"file_{i}")
                t = time.perf_counter_ns()
                fd, _ = eng.open(path, direct=direct)
                lat.append(time.perf_counter_ns() - t)
                fds.append(fd)
            return lat, fds
        except BaseException:
            for fd in fds:
                eng.close(fd)
            raise

    remounted = _remount(cfg)
    t0 = time.perf_counter()
    cold_lat, fds = open_pass()
    for fd in fds:
        eng.close(fd)
    hot_lat, fds = open_pass()
    try:
        if w.hold_seconds:
            time.sleep(w.hold_seconds)
    finally:
        for fd in fds:
            eng.close(fd)
    wall = time.perf_counter() - t0
    res = RunResult(
        workload="open_file",
        config=cfg.to_dict(),
        wall_seconds=wall,
        summaries={
            "open": summarize_ns(np.array(cold_lat + hot_lat)),
            "open_cold": summarize_ns(np.array(cold_lat)),
            "open_hot": summarize_ns(np.array(hot_lat)),
        },
    )
    res.extra["open_files"] = len(fds)
    res.extra["cold_via_remount"] = remounted
    return res


# ------------------------------------------------------------------- #15 --
def run_ssd_compare(cfg: BenchConfig, direct: bool = True) -> RunResult:
    """Block-latency percentile bench (the reference's most complete driver,
    ssd_test/main.go): identity offsets for seq, Fisher-Yates-equivalent
    shuffle for random (:118-128 — all threads share ONE pattern, which we
    keep, but build it once with a seeded RNG before fan-out, so there is no
    shared-state race). Per-thread latency arrays are merged post-join (the
    reference's global append raced, :80). Report = the §3.4 percentile
    block."""
    w = cfg.workload
    eng = _engine_or_raise()
    n = w.threads
    block = w.block_size_kb * KB
    fsize = w.file_size_mb * 1024 * KB
    n_blocks = max(1, fsize // block)
    offsets = np.arange(n_blocks, dtype=np.int64) * block
    if w.read_type == "random":
        rng = np.random.Generator(np.random.Philox(w.seed))
        rng.shuffle(offsets)  # one shared pattern, built before fan-out
    elif w.read_type != "seq":
        raise ValueError(f"read_type must be seq|random, got {w.read_type!r}")

    lat_all: list[np.ndarray] = [np.empty(0)] * n
    totals = [0] * n

    def worker(i: int, cancel) -> None:
        # Reference file layout: Workload.<i>/0 (ssd_test/main.go:41).
        path = os.path.join(w.dir, f"Workload.{i}", "0")
        size = eng.file_size(path)
        if size != fsize:
            raise ValueError(f"{path}: size {size} != configured {fsize}")
        fd, _ = eng.open(path, direct=direct)
        buf = eng.alloc(block)
        lats = []
        try:
            for _ in range(w.read_count):
                if cancel.is_set():
                    break
                total, lat = eng.pread_blocks(fd, buf, block, offsets)
                totals[i] += total
                lats.append(lat)
        finally:
            eng.close(fd)
            buf.free()
        if lats:
            lat_all[i] = np.concatenate(lats)

    t0 = time.perf_counter()
    WorkerGroup(abort_on_error=w.abort_on_error).run(n, worker, name="ssd")
    wall = time.perf_counter() - t0

    merged = np.concatenate([a for a in lat_all if a.size])
    total_bytes = sum(totals)
    res = RunResult(
        workload="ssd_compare",
        config=cfg.to_dict(),
        bytes_total=total_bytes,
        wall_seconds=wall,
        gbps=(total_bytes / 1e9) / wall if wall > 0 else 0.0,
        summaries={"block_read": summarize_ns(merged)},
    )
    res.extra["read_type"] = w.read_type
    res.extra["blocks_per_pass"] = int(n_blocks)
    return res
