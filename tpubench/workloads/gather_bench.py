"""ICI collective micro-benchmark: bandwidth vs mesh size, per collective.

The reference has no inter-worker communication to measure; its closest
transport benchmark is the gRPC/DirectPath client path (SURVEY §5.8). The
TPU-native framework's transport IS the XLA collective set, so each gets
its own benchmark mode: for every device count n (powers of two up to the
host's chips), shard a buffer over an n-chip 1-D mesh and time the jitted
collective, reporting effective per-chip bandwidth.

Modes and their ICI byte accounting (ring-schedule algebra; per collective
invocation):

* ``all_gather`` (and ``ring``, the explicit ppermute ring) — each chip
  receives the other n-1 shards: per-chip ``shard × (n-1)``, total
  ``shard × n × (n-1)``.
* ``reduce_scatter`` (``psum_scatter``) — each chip sends/receives
  ``shard × (n-1)/n``; total ``shard × (n-1)``.
* ``psum`` (all-reduce) — reduce-scatter + all-gather:
  per-chip ``2 × shard × (n-1)/n``; total ``2 × shard × (n-1)``.
"""

from __future__ import annotations

import time

import numpy as np

import jax

from tpubench.config import BenchConfig
from tpubench.dist.reassemble import (
    make_allreduce,
    make_mesh,
    make_reassemble,
    make_reduce_scatter,
    make_ring_reassemble,
    shard_to_device_array,
)
from tpubench.metrics.report import RunResult


def run_gather_bench(
    cfg: BenchConfig,
    shard_mb: float = 4.0,
    reps: int = 5,
    ring: bool = False,
    collective: str = "",
) -> RunResult:
    mode = collective or ("ring" if ring else "all_gather")
    if mode not in ("all_gather", "ring", "reduce_scatter", "psum"):
        raise ValueError(f"unknown collective {mode!r}")
    lane = cfg.staging.lane
    devices = jax.devices()
    shard_bytes = int(shard_mb * 1024 * 1024) // lane * lane
    rows = []
    n = 2
    sizes = []
    while n <= len(devices):
        sizes.append(n)
        n *= 2
    single_device = not sizes
    # reduce_scatter splits rows across chips: keep rows divisible by the
    # largest swept mesh size so every sweep point gets a static equal
    # split (and the byte-accounting // n divisions stay exact).
    max_n = sizes[-1] if sizes else 1
    shard_bytes = shard_bytes // (lane * max_n) * (lane * max_n) or lane * max_n
    if single_device:
        # One chip: there is no ICI to exercise — the gather lowers to an
        # identity. Run it anyway (sane CLI behavior on a 1-chip host) and
        # label the result clearly instead of reporting it as collective
        # bandwidth.
        sizes = [1]

    rng = np.random.default_rng(0)
    for n in sizes:
        mesh = make_mesh(devices[:n], axis=cfg.dist.mesh_axis)
        shards = [
            rng.integers(0, 256, (shard_bytes,), dtype=np.uint8) for _ in range(n)
        ]
        arr = shard_to_device_array(shards, mesh, cfg.dist.mesh_axis, lane)
        make = {
            "all_gather": make_reassemble,
            "ring": make_ring_reassemble,
            "reduce_scatter": make_reduce_scatter,
            "psum": make_allreduce,
        }[mode]
        fn = make(mesh, cfg.dist.mesh_axis)
        unary = mode in ("reduce_scatter", "psum")  # no checksum output
        first = fn(arr) if unary else fn(arr)[0]
        jax.block_until_ready(first)  # compile, uncounted
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn(arr) if unary else fn(arr)[0]
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / reps  # per-collective mean
        # ICI bytes per invocation (module docstring): per-chip and total.
        if mode in ("all_gather", "ring"):
            per_chip_bytes = shard_bytes * (n - 1)
            total_bytes = shard_bytes * n * (n - 1)
        elif mode == "reduce_scatter":
            per_chip_bytes = shard_bytes * (n - 1) // n
            total_bytes = shard_bytes * (n - 1)
        else:  # psum
            per_chip_bytes = 2 * shard_bytes * (n - 1) // n
            total_bytes = 2 * shard_bytes * (n - 1)
        per_chip_rx = per_chip_bytes / dt / 1e9 if dt > 0 else 0.0
        rows.append(
            {
                "devices": n,
                "shard_bytes": shard_bytes,
                "seconds": dt,
                "reps": reps,
                "ici_bytes_moved": total_bytes,  # per invocation
                "per_chip_rx_gbps": per_chip_rx,
                "total_gbps": total_bytes / dt / 1e9 if dt > 0 else 0.0,
            }
        )

    # Headline fields are SELF-CONSISTENT sweep aggregates: gbps equals
    # bytes_total / wall_seconds by construction (every row's per-gather
    # bytes and per-gather mean seconds scaled by the same reps), and
    # gbps_per_chip = gbps / n_chips like every other workload. The
    # per-mesh-size picture (including the best row) lives in extras.
    bytes_total = sum(r["ici_bytes_moved"] for r in rows) * reps
    wall = sum(r["seconds"] for r in rows) * reps
    n_chips = max(r["devices"] for r in rows)
    gbps = (bytes_total / 1e9) / wall if wall > 0 else 0.0
    best = max(rows, key=lambda r: r["per_chip_rx_gbps"])
    res = RunResult(
        workload="gather_bench",
        config=cfg.to_dict(),
        bytes_total=bytes_total,
        wall_seconds=wall,
        gbps=gbps,
        gbps_per_chip=gbps / n_chips,
        n_chips=n_chips,
        errors=0,
    )
    res.extra.update(
        {
            "mode": mode,
            "scaling": rows,
            "best": best,
            "single_device": single_device,
        }
    )
    return res
