"""ICI collective micro-benchmark: all-gather bandwidth vs mesh size.

The reference has no inter-worker communication to measure; its closest
transport benchmark is the gRPC/DirectPath client path (SURVEY §5.8). The
TPU-native framework's transport IS the ICI collective, so it gets its own
benchmark: for each device count n (powers of two up to the host's chips),
shard a buffer over an n-chip 1-D mesh and time the jitted all-gather (XLA
lowering and, optionally, the explicit ppermute ring), reporting effective
per-chip collective bandwidth.

Bandwidth definition: one all-gather moves ``shard_bytes × n × (n-1)`` bytes
over ICI in total (each chip receives the other n-1 shards); per-chip
receive bandwidth is ``shard_bytes × (n-1) / t``.
"""

from __future__ import annotations

import time

import numpy as np

import jax

from tpubench.config import BenchConfig
from tpubench.dist.reassemble import (
    make_mesh,
    make_reassemble,
    make_ring_reassemble,
    shard_to_device_array,
)
from tpubench.metrics.report import RunResult


def run_gather_bench(
    cfg: BenchConfig,
    shard_mb: float = 4.0,
    reps: int = 5,
    ring: bool = False,
) -> RunResult:
    lane = cfg.staging.lane
    devices = jax.devices()
    shard_bytes = int(shard_mb * 1024 * 1024) // lane * lane
    rows = []
    n = 2
    sizes = []
    while n <= len(devices):
        sizes.append(n)
        n *= 2
    single_device = not sizes
    if single_device:
        # One chip: there is no ICI to exercise — the gather lowers to an
        # identity. Run it anyway (sane CLI behavior on a 1-chip host) and
        # label the result clearly instead of reporting it as collective
        # bandwidth.
        sizes = [1]

    rng = np.random.default_rng(0)
    for n in sizes:
        mesh = make_mesh(devices[:n], axis=cfg.dist.mesh_axis)
        shards = [
            rng.integers(0, 256, (shard_bytes,), dtype=np.uint8) for _ in range(n)
        ]
        arr = shard_to_device_array(shards, mesh, cfg.dist.mesh_axis, lane)
        fn = (make_ring_reassemble if ring else make_reassemble)(
            mesh, cfg.dist.mesh_axis
        )
        jax.block_until_ready(fn(arr)[0])  # compile, uncounted
        t0 = time.perf_counter()
        for _ in range(reps):
            gathered, _ = fn(arr)
        jax.block_until_ready(gathered)
        dt = (time.perf_counter() - t0) / reps  # per-gather mean
        per_chip_rx = shard_bytes * (n - 1) / dt / 1e9 if dt > 0 else 0.0
        rows.append(
            {
                "devices": n,
                "shard_bytes": shard_bytes,
                "seconds": dt,
                "reps": reps,
                "ici_bytes_moved": shard_bytes * n * (n - 1),  # per gather
                "per_chip_rx_gbps": per_chip_rx,
                "total_gbps": shard_bytes * n * (n - 1) / dt / 1e9 if dt > 0 else 0.0,
            }
        )

    # Headline fields are SELF-CONSISTENT sweep aggregates: gbps equals
    # bytes_total / wall_seconds by construction (every row's per-gather
    # bytes and per-gather mean seconds scaled by the same reps), and
    # gbps_per_chip = gbps / n_chips like every other workload. The
    # per-mesh-size picture (including the best row) lives in extras.
    bytes_total = sum(r["ici_bytes_moved"] for r in rows) * reps
    wall = sum(r["seconds"] for r in rows) * reps
    n_chips = max(r["devices"] for r in rows)
    gbps = (bytes_total / 1e9) / wall if wall > 0 else 0.0
    best = max(rows, key=lambda r: r["per_chip_rx_gbps"])
    res = RunResult(
        workload="gather_bench",
        config=cfg.to_dict(),
        bytes_total=bytes_total,
        wall_seconds=wall,
        gbps=gbps,
        gbps_per_chip=gbps / n_chips,
        n_chips=n_chips,
        errors=0,
    )
    res.extra.update(
        {
            "mode": "ring" if ring else "all_gather",
            "scaling": rows,
            "best": best,
            "single_device": single_device,
        }
    )
    return res
