"""``tpubench meta-storm`` — open-loop metadata storms over many small
objects.

The reference's ``list_operation``/``open_file`` binaries measure
metadata closed-loop; this workload drives the PR-10 arrivals plane
(seeded Poisson/MMPP/diurnal) over a weighted list/stat/open mix so
metadata gets what the serve plane gave reads: offered-vs-achieved rate,
queue-inclusive latency, and — under ``--meta-sweep`` — the
latency-vs-load curve with the saturation knee identified. List ops ride
``maxResults`` pagination (multi-page listings on the wire backends).
"""

from __future__ import annotations

import time

from tpubench.config import BenchConfig
from tpubench.lifecycle.storm import build_storm_schedule, run_storm
from tpubench.metrics.report import RunResult
from tpubench.obs.flight import (
    flight_from_config,
    host_journal_path,
    transport_label,
)
from tpubench.storage import open_backend
from tpubench.storage.base import deterministic_bytes


def populate_meta_objects(backend, prefix: str, count: int,
                          size: int) -> list[str]:
    """The many-small-objects population (idempotent: re-running a storm
    against the same store just overwrites the same names)."""
    names = []
    for i in range(count):
        name = f"{prefix}meta/{i:05d}"
        backend.write(name, deterministic_bytes(name, size).tobytes())
        names.append(name)
    return names


def _storm_point(cfg: BenchConfig, backend, names: list[str],
                 rate_rps: float, flight, tlabel: str,
                 ledger=None) -> dict:
    lc = cfg.lifecycle
    schedule = build_storm_schedule(
        names,
        kind=lc.meta_arrival,
        rate_rps=rate_rps,
        duration_s=lc.meta_duration_s,
        mix=lc.meta_mix,
        prefix=f"{lc.prefix}meta/",
        seed=lc.seed,
        burst_factor=cfg.serve.burst_factor,
        burst_fraction=cfg.serve.burst_fraction,
        burst_cycle_s=cfg.serve.burst_cycle_s,
        diurnal_period_s=cfg.serve.diurnal_period_s,
    )
    return run_storm(
        backend, schedule,
        workers=lc.meta_workers,
        page_size=lc.meta_page_size,
        read_bytes=lc.meta_read_bytes,
        flight=flight,
        transport_label=tlabel,
        ledger=ledger,
    )


def run_meta_storm(cfg: BenchConfig, backend=None,
                   sweep: bool = False) -> RunResult:
    lc = cfg.lifecycle
    owns = backend is None
    backend = backend or open_backend(cfg)
    flight = flight_from_config(cfg)
    tlabel = transport_label(cfg)

    # Live telemetry (short workload, same wiring as pod-ingest: the
    # registry taps every meta record; `tpubench top` can watch).
    from tpubench.obs.telemetry import telemetry_from_config

    jpath = (
        host_journal_path(
            cfg.obs.flight_journal, cfg.dist.process_id,
            cfg.dist.num_processes,
        )
        if cfg.obs.flight_journal else None
    )
    tel = telemetry_from_config(cfg)
    if tel is not None:
        tel.resource["workload"] = "meta_storm"
        if flight is not None:
            tel.attach_flight(flight)
            if jpath:
                tel.stream_journal(
                    flight, jpath,
                    extra_fn=lambda: {"workload": "meta_storm"},
                    max_bytes=cfg.obs.journal_max_bytes,
                )
        tel.start()

    import contextlib

    try:
        t0 = time.perf_counter()
        names = populate_meta_objects(
            backend, lc.prefix, lc.meta_objects, lc.meta_object_bytes
        )
        with (flight.activate() if flight is not None
              else contextlib.nullcontext()):
            if sweep:
                points = []
                for mult in lc.sweep_points:
                    out = _storm_point(
                        cfg, backend, names, lc.meta_rate_rps * mult,
                        flight, tlabel,
                    )
                    points.append({
                        "multiplier": mult,
                        "offered_rps": out["offered_rps"],
                        "achieved_rps": out["achieved_rps"],
                        "p50_ms": out["p50_ms"],
                        "p99_ms": out["p99_ms"],
                        "errors": out["errors"],
                        "completed": out["completed"],
                    })
                from tpubench.serve.qos import find_knee

                last = out
                lifecycle = {
                    "op": "meta_storm",
                    "objects": lc.meta_objects,
                    "mix": lc.meta_mix,
                    "arrival": lc.meta_arrival,
                    "page_size": lc.meta_page_size,
                    "sweep": {
                        "points": points,
                        "knee": find_knee(points),
                    },
                    **{k: last[k] for k in (
                        "ops", "completed", "errors", "bytes",
                        "list_items", "sleep_scale",
                    )},
                }
                total_bytes = last["bytes"]
                errors = sum(p["errors"] for p in points)
            else:
                out = _storm_point(
                    cfg, backend, names, lc.meta_rate_rps, flight, tlabel
                )
                lifecycle = {
                    "op": "meta_storm",
                    "objects": lc.meta_objects,
                    "mix": lc.meta_mix,
                    "arrival": lc.meta_arrival,
                    "page_size": lc.meta_page_size,
                    **out,
                }
                total_bytes = out["bytes"]
                errors = out["errors"]
        wall = time.perf_counter() - t0
    finally:
        if tel is not None:
            tel_summary = tel.close()
        if owns:
            backend.close()

    res = RunResult(
        workload="meta_storm",
        config=cfg.to_dict(),
        bytes_total=total_bytes,
        wall_seconds=wall,
        gbps=(total_bytes / 1e9) / wall if wall > 0 else 0.0,
        errors=errors,
    )
    res.extra["lifecycle"] = lifecycle
    if tel is not None and tel_summary is not None:
        res.extra["telemetry"] = tel_summary
    if flight is not None:
        res.extra["flight"] = flight.summary()
        if jpath:
            res.extra["flight_journal"] = flight.write_journal(
                jpath, extra={"workload": "meta_storm"},
                max_bytes=cfg.obs.journal_max_bytes,
            )
    return res
