"""Pod-scale ingest: fetch → stage → gather, each stage timed separately.

The north-star workload (BASELINE.json): ONE logical object's byte-range
shards fanned across the pod's chips (CP-analog of the reference's
block-decomposition loop, ``ssd_test/main.go:112-128``), fetched
concurrently per shard over the storage backend, staged into each chip's
HBM, then reassembled with an ICI all-gather so every chip holds the full
object — the pod, not a VM, is the unit under test.

Stage separation (SURVEY hard-part (c)): fetch and stage are timed on the
host around blocking boundaries; gather is timed around
``block_until_ready`` on the jitted collective, with a warmup call first so
compile time is reported separately, never folded into the collective time.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Optional

import numpy as np

import jax

from tpubench.config import BenchConfig
from tpubench.dist.reassemble import (
    gathered_to_bytes,
    local_mesh_devices,
    make_mesh,
    make_reassemble,
    make_ring_reassemble,
    shard_to_device_array,
)
from tpubench.dist.shard import ShardTable
from tpubench.metrics.report import RunResult
from tpubench.obs.flight import (
    flight_from_config,
    host_journal_path,
    transport_label,
)
from tpubench.obs.tracing import trace_scope
from tpubench.storage import open_backend
from tpubench.storage.base import StorageBackend
from tpubench.workloads.common import (
    WorkerGroup,
    fetch_shard,
    fetch_shards_mux,
    global_hole_totals,
    zero_failed_shards,
)


@dataclass
class PodIngestWorkload:
    cfg: BenchConfig
    backend: StorageBackend
    ring: bool = False  # explicit ppermute ring instead of XLA all_gather
    verify: bool = True

    def run(self, object_name: Optional[str] = None) -> RunResult:
        from tpubench.obs.exporters import cloud_exporter_from_config

        # Construct up front: a live-mode misconfiguration (missing lib,
        # bad creds) must fail BEFORE the benchmark runs, not discard a
        # completed run's result afterwards.
        cloud_exp = cloud_exporter_from_config(self.cfg)

        w = self.cfg.workload
        lane = self.cfg.staging.lane
        name = object_name or f"{w.object_name_prefix}0"
        mesh = make_mesh(axis=self.cfg.dist.mesh_axis)
        n = int(mesh.devices.size)
        size = self.backend.stat(name).size
        table = ShardTable.build(size, n, align=lane)

        # ---- fetch: this host fetches ONLY its local chips' byte ranges --
        # (multi-host SPMD: fetch stays on the host that owns the chip; the
        # only cross-host byte movement is the ICI all-gather below).
        all_devices = list(mesh.devices.reshape(-1))
        pid = jax.process_index()
        local_idx = [i for i, d in enumerate(all_devices) if d.process_index == pid]
        buffers = [np.zeros(table.shard_bytes, dtype=np.uint8) for _ in local_idx]

        # Flight recorder: one record per shard fetch (connect/stream_open/
        # first_byte emitted down-stack via the thread-local channel) plus
        # one pod-level record spanning fetch→stage→gather.
        flight = flight_from_config(self.cfg)
        tlabel = transport_label(self.cfg)

        # Live telemetry: short burst workload, but the registry still
        # taps every shard record (per-phase histograms + byte counters)
        # and the endpoint stays scrapeable for the run's duration.
        from tpubench.obs.telemetry import telemetry_from_config

        jpath_stream = (
            host_journal_path(
                self.cfg.obs.flight_journal, pid, jax.process_count()
            )
            if self.cfg.obs.flight_journal else None
        )
        tel = telemetry_from_config(self.cfg)
        tel_summary = None
        if tel is not None:
            tel.resource["workload"] = "pod_ingest"
            tel.set_chips(n)
            if flight is not None:
                tel.attach_flight(flight)
                if jpath_stream:
                    tel.stream_journal(
                        flight, jpath_stream,
                        extra_fn=lambda: {
                            "workload": "pod_ingest", "n_chips": n,
                            "chips_global": True,
                        },
                        max_bytes=self.cfg.obs.journal_max_bytes,
                    )
            tel.start()

        # install=False: the pod op is a side-channel record — installing
        # it on this (main) thread would leave the thread's op and trace
        # position dangling if the run aborts before finish, poisoning
        # every later trace begun on this thread with a dead parent. The
        # shard reads parent under it EXPLICITLY via trace_scope instead.
        pod_op = (
            flight.worker("pod").begin(name, tlabel, kind="object",
                                       install=False)
            if flight is not None else None
        )
        pod_ctx = pod_op.trace_context() if pod_op is not None else None

        def fetch(k: int, cancel) -> None:
            # The shard read joins the object span's trace (the "object →
            # shard read" tree edge) even though it runs on a worker
            # thread that inherited no ambient context.
            with trace_scope(pod_ctx):
                op = (
                    flight.worker(f"shard{local_idx[k]}").begin(name, tlabel)
                    if flight is not None else None
                )
                try:
                    fetch_shard(self.backend, name, table,
                                local_idx[k], buffers[k])
                except BaseException as e:
                    if op is not None:
                        op.finish(error=e)
                    raise
                if op is not None:
                    op.mark("body_complete")
                    op.finish(table.shard(local_idx[k]).length)

        t0 = time.perf_counter()
        try:
            gres = fetch_shards_mux(
                self.backend, self.cfg, name, table, local_idx, buffers
            )
            if gres is None:
                gres = WorkerGroup(abort_on_error=w.abort_on_error).run(
                    len(local_idx), fetch, name="fetch"
                )
        except BaseException as e:
            # An aborting fetch must still close the object record: the
            # journal keeps the errored span instead of silently losing
            # the object that died.
            if pod_op is not None:
                pod_op.finish(error=e)
            raise
        t_fetch = time.perf_counter() - t0
        if pod_op is not None:
            pod_op.mark("body_complete")

        # Failure domains (SURVEY §5.3): with abort_on_error=False a failed
        # shard does not abort the pod — its buffer is zeroed so the gather
        # carries a deterministic HOLE, reported below (shard indices +
        # missing bytes) instead of crashing the run.
        holes = zero_failed_shards(gres, table, buffers, local_idx)

        # ---- stage: host shard buffers → per-chip HBM --------------------
        t0 = time.perf_counter()
        global_arr = shard_to_device_array(buffers, mesh, self.cfg.dist.mesh_axis, lane)
        jax.block_until_ready(global_arr)
        t_stage = time.perf_counter() - t0
        if pod_op is not None:
            pod_op.mark("hbm_staged")

        # ---- gather: ICI all-gather (compile excluded via warmup) --------
        fn = (make_ring_reassemble if self.ring else make_reassemble)(
            mesh, self.cfg.dist.mesh_axis
        )
        t0 = time.perf_counter()
        jax.block_until_ready(fn(global_arr))  # warmup/compile
        t_compile = time.perf_counter() - t0
        t0 = time.perf_counter()
        gathered, csum = fn(global_arr)
        jax.block_until_ready(gathered)
        t_gather = time.perf_counter() - t0
        if pod_op is not None:
            pod_op.mark("gather_complete")

        # ---- verify ------------------------------------------------------
        ok = True
        if self.verify:
            if jax.process_count() == 1:
                # Single controller: full equality + global checksum.
                host_sum = sum(
                    int(b.sum(dtype=np.uint64)) for b in buffers
                ) % (1 << 32)
                ok = int(jax.device_get(csum)) % (1 << 32) == host_sum
                got = gathered_to_bytes(gathered, size)
                expected = b"".join(b.tobytes() for b in buffers)
                ok = ok and got == expected[:size]
            else:
                # Multi-host: each process checks that its fetched shards
                # appear at the right offsets of the (replicated) gather;
                # the dedicated multihost test does full-content equality
                # via deterministic objects.
                garr = np.asarray(jax.device_get(gathered)).reshape(n, -1)
                ok = all(
                    bytes(garr[i].tobytes()) == buffers[k].tobytes()
                    for k, i in enumerate(local_idx)
                )

        wall = t_fetch + t_stage + t_gather
        # Throughput counts DELIVERED bytes: holes moved nothing, so a
        # degraded run must not report healthy-looking bandwidth. Hole
        # totals are aggregated pod-wide (a failing shard on ANOTHER host
        # degrades this host's gathered object just the same).
        ghole = global_hole_totals(holes)
        delivered = size - ghole["bytes"]
        res = RunResult(
            workload="pod_ingest",
            config=self.cfg.to_dict(),
            bytes_total=delivered,
            wall_seconds=wall,
            gbps=(delivered / 1e9) / wall if wall > 0 else 0.0,
            gbps_per_chip=((delivered / 1e9) / wall / n) if wall > 0 else 0.0,
            n_chips=n,
            errors=ghole["shards"] + (0 if ok else 1),
        )
        res.extra.update(
            {
                "holes": holes,  # this process's failed shards
                "holes_global": ghole,  # pod-wide totals used for delivered bytes
                "mode": "ring" if self.ring else "all_gather",
                "fetch_seconds": t_fetch,
                "stage_seconds": t_stage,
                "gather_seconds": t_gather,
                "compile_seconds": t_compile,
                "object_size": size,
                "fetch_gbps": (delivered / 1e9) / t_fetch if t_fetch > 0 else 0.0,
                "stage_gbps": (delivered / 1e9) / t_stage if t_stage > 0 else 0.0,
                # ICI traffic: each chip receives the other n-1 shards.
                "gather_gbps": (delivered / 1e9) / t_gather if t_gather > 0 else 0.0,
                "ici_bytes_moved": table.shard_bytes * n * (n - 1),
                "verified": ok,
                "shard_bytes": table.shard_bytes,
            }
        )
        if pod_op is not None:
            pod_op.finish(delivered)
        if tel is not None:
            # The pod record above was the last append: registry final.
            # (All session threads are daemons, so an aborting run can
            # never be held open by its observer.)
            tel_summary = tel.close()
            res.extra["telemetry"] = tel_summary
        if flight is not None:
            res.extra["flight"] = flight.summary()
            if jpath_stream:
                res.extra["flight_journal"] = flight.write_journal(
                    jpath_stream,
                    extra={"workload": "pod_ingest", "n_chips": n,
                           "chips_global": True},
                    max_bytes=self.cfg.obs.journal_max_bytes,
                )
        # One-burst workload: cloud export is a single final flush of the
        # stage-separated numbers (the periodic loop belongs to the long
        # runners — read and stream).
        if cloud_exp is not None:
            for key in ("fetch_gbps", "stage_gbps", "gather_gbps"):
                cloud_exp.export_point(key, res.extra[key])
            cloud_exp.export_point("bytes_ingested", float(delivered))
            cloud_exp.export_point("ingest_gbps", res.gbps)
            cloud_exp.close()
            res.extra["metrics_export"] = cloud_exp.summary()
        return res


def run_pod_ingest(
    cfg: BenchConfig,
    backend: Optional[StorageBackend] = None,
    ring: bool = False,
    verify: bool = True,
    object_name: Optional[str] = None,
) -> RunResult:
    owns = backend is None
    backend = backend or open_backend(cfg)
    try:
        return PodIngestWorkload(cfg, backend, ring=ring, verify=verify).run(object_name)
    finally:
        if owns:
            backend.close()
