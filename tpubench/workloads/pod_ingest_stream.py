"""Streamed pod ingest: a pipeline of objects with fetch ∥ stage+gather
overlap — the I/O analog of pipeline parallelism (SURVEY §2.6 PP row).

``pod_ingest`` measures one object with strict stage separation; this
driver ingests a *sequence* of objects the way a training job consumes a
dataset: while object *k* is being staged to HBM and all-gathered over ICI,
a background fetcher is already pulling object *k+1*'s local byte-range
shards into the second host-buffer set (double buffering at the object
level; the granule-level double buffering lives in
:mod:`tpubench.staging.device`).

Reports per-stage seconds (summed), wall time, and the overlap efficiency
``(fetch + device) / wall`` — 1.0 means no overlap, 2.0 means perfect
fetch/device overlap.

Periodic per-host JSON snapshots (SURVEY §5.4) make long streams
restartABLE, not just inspectable: ``resume_from`` loads a prior run's
snapshot and continues at its ``resume_point`` — the count of
consecutively hole-free objects, so degraded objects are re-fetched
rather than baked in. Snapshot counters never regress across resumes;
``bytes`` counts complete objects only (partial deliveries live in each
run's result, not the checkpoint), so re-fetches never double-count.
"""

from __future__ import annotations

import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Optional

import numpy as np

import jax

from tpubench.config import BenchConfig
from tpubench.dist.reassemble import make_mesh, make_reassemble, shard_to_device_array
from tpubench.dist.shard import ShardTable
from tpubench.metrics.report import RunResult
from tpubench.obs.exporters import (
    PeriodicExporter,
    SnapshotWriter,
    cloud_exporter_from_config,
)
from tpubench.obs.flight import (
    adopt_op,
    flight_from_config,
    host_journal_path,
    transport_label,
)
from tpubench.obs.profiling import annotate
from tpubench.obs.tracing import trace_scope
from tpubench.storage import open_backend
from tpubench.storage.base import StorageBackend
from tpubench.workloads.common import (
    WorkerGroup,
    fetch_shard,
    fetch_shards_mux,
    global_hole_totals,
    zero_failed_shards,
)


@dataclass
class _ObjectPlan:
    name: str
    size: int
    table: ShardTable


class StreamedPodIngest:
    def __init__(
        self,
        cfg: BenchConfig,
        backend: StorageBackend,
        n_objects: int,
        verify: bool = False,
        snapshot_path: Optional[str] = None,
        resume_from: Optional[str] = None,
    ):
        self.cfg = cfg
        self.backend = backend
        self.n_objects = n_objects
        self.verify = verify
        self.snapshot_path = snapshot_path
        # Resume (SURVEY §5.4 upgraded from restart-inspectable to
        # restartable): a prior run's snapshot names the objects already
        # delivered; this run skips them and continues the stream. The
        # object sequence is deterministic (prefix + k), so "objects_done
        # = N" identifies exactly the first N stream positions.
        self.resume_from = resume_from
        self._progress: dict = {"objects_done": 0, "bytes": 0}
        # Flight recorder: per-shard fetch records + one record per
        # streamed object (fetch→stage→gather), journaled per host.
        self._flight = flight_from_config(cfg)
        self._tlabel = transport_label(cfg)

    def _fetch_local(self, plan: _ObjectPlan, buffers: list[np.ndarray],
                     local_idx, parent_ctx=None):
        w = self.cfg.workload
        flight = self._flight

        def fetch(k: int, cancel) -> None:
            # fetch_shard zeroes the pad tail — essential here because the
            # double-buffer sets are REUSED across objects of differing
            # sizes; stale bytes would otherwise be gathered as padding.
            # parent_ctx (the object span) makes the shard read a child
            # of its object in the trace tree even though this worker
            # thread inherited no ambient context.
            with trace_scope(parent_ctx):
                op = (
                    flight.worker(f"shard{local_idx[k]}").begin(
                        plan.name, self._tlabel
                    )
                    if flight is not None else None
                )
                try:
                    fetch_shard(self.backend, plan.name, plan.table,
                                local_idx[k], buffers[k])
                except BaseException as e:
                    if op is not None:
                        op.finish(error=e)
                    raise
                if op is not None:
                    op.mark("body_complete")
                    op.finish(plan.table.shard(local_idx[k]).length)

        gres = fetch_shards_mux(
            self.backend, self.cfg, plan.name, plan.table, local_idx, buffers
        )
        if gres is None:
            gres = WorkerGroup(abort_on_error=w.abort_on_error).run(
                len(local_idx), fetch, name="stream-fetch"
            )
        # Failure domains (SURVEY §5.3): zero failed shards (deterministic
        # holes — critical with reused buffers, which would otherwise leak
        # the PREVIOUS object's bytes into this one) and report them in the
        # same {"shards", "bytes"} shape pod_ingest uses.
        return zero_failed_shards(gres, plan.table, buffers, local_idx)

    def run(self) -> RunResult:
        w = self.cfg.workload
        lane = self.cfg.staging.lane
        mesh = make_mesh(axis=self.cfg.dist.mesh_axis)
        n = int(mesh.devices.size)
        pid = jax.process_index()
        all_devices = list(mesh.devices.reshape(-1))
        local_idx = [i for i, d in enumerate(all_devices) if d.process_index == pid]

        names = [f"{w.object_name_prefix}{k % max(1, w.workers)}" for k in range(self.n_objects)]
        plans = []
        for name in names:
            size = self.backend.stat(name).size
            plans.append(_ObjectPlan(name, size, ShardTable.build(size, n, align=lane)))
        shard_bytes = max(p.table.shard_bytes for p in plans)

        # Multi-host: each process owns its snapshot/resume file (process 0
        # keeps the bare path, so single-host usage is unchanged) — two
        # hosts must never race on one checkpoint file.
        def _host_path(path: Optional[str]) -> Optional[str]:
            if not path or jax.process_count() == 1 or pid == 0:
                return path
            return f"{path}.p{pid}"

        snapshot_path = _host_path(self.snapshot_path)
        resume_path = _host_path(self.resume_from)

        start_k = 0
        prior: Optional[dict] = None
        prior_bytes = 0
        prior_done = 0
        prior_resume = 0
        if resume_path:
            from tpubench.obs.exporters import load_snapshot

            # Crash-tolerant load: a torn/partial snapshot (killed
            # mid-flush) is a one-line warning + fresh start, never a
            # traceback that blocks the resume path entirely.
            prior = load_snapshot(resume_path)
            if prior is not None:
                # resume_point = consecutively COMPLETE objects from stream
                # start (objects delivered with holes do not advance it, so
                # a resume re-fetches them instead of baking the holes in).
                # The monitoring floor comes from the prior objects_done
                # separately — a holed run has objects_done > resume_point
                # and neither may regress.
                prior_resume = int(
                    prior.get("resume_point", prior.get("objects_done", 0))
                )
                prior_done = int(prior.get("objects_done", prior_resume))
        if jax.process_count() > 1:
            # Every loop iteration runs pod collectives, so the resume
            # point must be AGREED pod-wide: per-host snapshots are
            # written on independent timers and can disagree after a
            # crash. The pod resumes at the minimum (a host whose
            # checkpoint is behind — or missing — forces a re-fetch of
            # the difference; unmatched collectives would hang the pod).
            from jax.experimental import multihost_utils

            prior_resume = int(
                np.min(multihost_utils.process_allgather(np.int64(prior_resume)))
            )
        start_k = min(prior_resume, self.n_objects)
        resume_point = max(
            prior_resume, start_k
        )  # > n_objects when a prior run got further
        # Snapshot "bytes" counts COMPLETE objects only (exactly the ones a
        # resume will not re-fetch): monotonic, recomputable from the
        # deterministic plan sizes, and immune to double counting when a
        # holed object is re-fetched. Partial deliveries show up in each
        # run's RunResult.bytes_total, not in the checkpoint.
        size_prefix = [0]
        for p in plans:
            size_prefix.append(size_prefix[-1] + p.size)

        def complete_bytes() -> int:
            if prior is not None and resume_point > self.n_objects:
                # A prior run completed more of the stream than this
                # invocation can see; its own accounting stands.
                return prior_bytes
            # Floor at the prior checkpoint value: counters must never
            # regress even when the prior snapshot used a different
            # accounting (older formats included partial deliveries).
            return max(prior_bytes, size_prefix[min(resume_point, self.n_objects)])

        prior_bytes = int(prior.get("bytes", 0)) if prior else 0
        self._progress = {
            "objects_done": max(start_k, prior_done),
            "resume_point": resume_point,
            "bytes": complete_bytes(),
        }

        # Two host-buffer sets: fetch into one while the other stages.
        buffer_sets = [
            [np.zeros(shard_bytes, dtype=np.uint8) for _ in local_idx] for _ in range(2)
        ]
        reassemble = make_reassemble(mesh, self.cfg.dist.mesh_axis)

        # Warm the first object's shape BEFORE the wall clock starts: the
        # one-off XLA compile would otherwise dominate short streams and
        # mask the fetch∥device overlap the efficiency metric reports.
        # Objects of other sizes still compile (once per shape) in-loop.
        compiled_shapes = set()
        if start_k < self.n_objects:
            rows0 = plans[start_k].table.shard_bytes // lane
            warm = shard_to_device_array(
                [b[: rows0 * lane] for b in buffer_sets[0]], mesh,
                self.cfg.dist.mesh_axis, lane,
            )
            jax.block_until_ready(reassemble(warm))
            compiled_shapes.add(warm.shape)
            del warm

        fetch_s = stage_s = gather_s = 0.0
        total_bytes = 0
        checks_ok = True
        object_checksums: list[int] = []
        # object idx → {"shards": [...], "bytes": n} (same leaf shape as
        # pod_ingest's extra["holes"], so result consumers parse one schema).
        object_holes: dict[int, dict] = {}

        def snapshot() -> dict:
            return dict(self._progress)

        snap_ctx = (
            SnapshotWriter(snapshot, snapshot_path, interval_s=5.0, process_index=pid)
            if snapshot_path
            else None
        )

        # Flight journal rides the same periodic flush machinery as the
        # progress snapshots (atomic per-host files; final flush
        # guaranteed), so a crashed stream still leaves a journal behind.
        flight = self._flight
        flight_path = (
            host_journal_path(
                self.cfg.obs.flight_journal, pid, jax.process_count()
            )
            if flight is not None and self.cfg.obs.flight_journal
            else None
        )
        # write_journal (not SnapshotWriter's raw dump) so in-run flushes
        # get the same .gz compression and size-bounded rotation as every
        # other journal writer; PeriodicExporter keeps the cadence + the
        # guaranteed final flush.
        flight_ctx = (
            PeriodicExporter(
                lambda: flight.write_journal(
                    flight_path,
                    extra={"workload": "pod_ingest_stream", "n_chips": n,
                           "chips_global": True},
                    max_bytes=self.cfg.obs.journal_max_bytes,
                ),
                interval_s=5.0,
            )
            if flight_path
            else None
        )

        # Live telemetry: flight tap + scrapeable endpoint; the journal
        # stream above already feeds `tpubench top`, so the session does
        # not double-write it.
        from tpubench.obs.telemetry import telemetry_from_config

        tel = telemetry_from_config(self.cfg)
        tel_summary = None
        if tel is not None:
            tel.resource["workload"] = "pod_ingest_stream"
            tel.set_chips(n)
            if flight is not None:
                tel.attach_flight(flight)
            tel.start()

        # In-run cloud export (metrics_exporter.go:36-58): stream progress
        # gauges every metrics_interval_s during the run + final flush — a
        # 30-minute stream emits series long before it finishes.
        cloud_exp = cloud_exporter_from_config(self.cfg)
        cloud_periodic = None

        def flush_progress() -> None:  # closes over t_wall0 (set below)
            p = dict(self._progress)
            elapsed = time.perf_counter() - t_wall0
            cloud_exp.export_point("objects_done", float(p.get("objects_done", 0)))
            cloud_exp.export_point("bytes_ingested", float(p.get("bytes", 0)))
            cloud_exp.export_point(
                "ingest_gbps",
                (p.get("bytes", 0) / 1e9) / elapsed if elapsed > 0 else 0.0,
            )

        pool = ThreadPoolExecutor(max_workers=1)
        t_wall0 = time.perf_counter()
        try:
            if snap_ctx:
                snap_ctx.__enter__()
            if flight_ctx:
                flight_ctx.__enter__()
            if cloud_exp is not None:
                cloud_periodic = PeriodicExporter(
                    flush_progress, self.cfg.obs.metrics_interval_s
                ).start()

            def timed_fetch(k: int):
                # Pool threads are REUSED across objects while the op is
                # finished by the MAIN loop (which cannot clear THIS
                # thread's installed-op slot): clear any stale op/trace
                # position first, or object k+1's op would parent under
                # object k's span — every object chained into one trace.
                adopt_op(None)
                # Object-level flight op opened HERE (the fetch thread):
                # the mux fetch path's connect/retry notes attach to it
                # via the thread-local channel; the main loop stamps the
                # stage/gather phases after the future resolves.
                op = (
                    flight.worker("stream").begin(
                        plans[k].name, self._tlabel, kind="object"
                    )
                    if flight is not None else None
                )
                t0 = time.perf_counter()
                with annotate(f"fetch/obj{k}"):
                    # On failure the op is deliberately NOT finished here:
                    # the "stream" ring's one appending owner is the main
                    # loop (finish after gather), and a pool-thread append
                    # could race it while it finishes the previous object.
                    # The exception aborts the run via the future; the
                    # in-flight record is dropped, never corrupted (the
                    # per-shard error records from _fetch_local survive).
                    holes = self._fetch_local(
                        plans[k], buffer_sets[k % 2], local_idx,
                        parent_ctx=(
                            op.trace_context() if op is not None else None
                        ),
                    )
                if op is not None:
                    op.mark("body_complete")
                    # Release this thread's slot NOW (the record stays
                    # in flight for the main loop's finish): the next
                    # object on this pool thread starts trace-clean.
                    adopt_op(None)
                return time.perf_counter() - t0, holes, op

            pending = (
                pool.submit(timed_fetch, start_k)
                if start_k < self.n_objects
                else None
            )
            for k in range(start_k, self.n_objects):
                # Object k's shards are on host.
                dt, holes, obj_op = pending.result()
                fetch_s += dt
                # Pod-wide totals (collective over DCN when multi-host —
                # called unconditionally so every process participates).
                ghole = global_hole_totals(holes)
                if ghole["shards"]:
                    object_holes[k] = {**holes, "global": ghole}
                if k + 1 < self.n_objects:
                    pending = pool.submit(timed_fetch, k + 1)  # overlap next fetch

                plan = plans[k]
                rows = plan.table.shard_bytes // lane
                shards = [b[: rows * lane] for b in buffer_sets[k % 2]]
                t0 = time.perf_counter()
                with annotate(f"stage/obj{k}"):
                    arr = shard_to_device_array(
                        shards, mesh, self.cfg.dist.mesh_axis, lane
                    )
                    jax.block_until_ready(arr)
                t1 = time.perf_counter()
                stage_s += t1 - t0
                if obj_op is not None:
                    obj_op.mark("hbm_staged")
                shape_key = arr.shape
                if shape_key not in compiled_shapes:
                    jax.block_until_ready(reassemble(arr))  # compile, uncounted
                    compiled_shapes.add(shape_key)
                    t1 = time.perf_counter()
                with annotate(f"gather/obj{k}"):
                    gathered, csum = reassemble(arr)
                    jax.block_until_ready(gathered)
                gather_s += time.perf_counter() - t1
                if obj_op is not None:
                    obj_op.mark("gather_complete")
                    obj_op.finish(plan.size - ghole["bytes"])
                # Delivered bytes only: holes moved nothing (see pod_ingest);
                # pod-wide totals so another host's failure counts here too.
                total_bytes += plan.size - ghole["bytes"]
                # The resume point advances only over consecutively
                # hole-free objects: a degraded object stays re-fetchable.
                if resume_point == k and not ghole["shards"]:
                    resume_point = k + 1
                if self.verify and jax.process_count() == 1:
                    # On-device checksum of the gathered pod array, exposed
                    # per object so callers can compare against the TRUE
                    # object bytes (an oracle independent of the host
                    # buffers — catches stale-padding-class bugs the
                    # host-vs-device comparison is blind to).
                    dev_sum = int(jax.device_get(csum))
                    object_checksums.append(dev_sum)
                    host = sum(int(s.sum(dtype=np.uint64)) for s in shards)
                    checks_ok = checks_ok and dev_sum == host % (1 << 32)
                # Per-transfer HBM hygiene (the staging executor's
                # delete() discipline): this object's staged shards and
                # gathered copy are consumed — release the device memory
                # now, not at GC's leisure N objects later, so a long
                # stream's HBM footprint is one object, not the history.
                for consumed in (arr, gathered):
                    delete = getattr(consumed, "delete", None)
                    if delete is not None:
                        delete()
                self._progress = {
                    "objects_done": max(k + 1, prior_done),
                    "resume_point": resume_point,
                    "bytes": complete_bytes(),
                    "fetch_seconds": fetch_s,
                    "stage_seconds": stage_s,
                    "gather_seconds": gather_s,
                }
        finally:
            pool.shutdown(wait=False, cancel_futures=True)
            if snap_ctx:
                snap_ctx.__exit__(None, None, None)
            if flight_ctx:
                flight_ctx.__exit__(None, None, None)  # final journal flush
            if cloud_periodic is not None:
                cloud_periodic.close()  # guaranteed final flush
                cloud_exp.close()
            if tel is not None:
                # The stream loop is done appending: registry final.
                tel_summary = tel.close()
        wall = time.perf_counter() - t_wall0

        device_s = stage_s + gather_s
        res = RunResult(
            workload="pod_ingest_stream",
            config=self.cfg.to_dict(),
            bytes_total=total_bytes,
            wall_seconds=wall,
            gbps=(total_bytes / 1e9) / wall if wall > 0 else 0.0,
            gbps_per_chip=((total_bytes / 1e9) / wall / n) if wall > 0 else 0.0,
            n_chips=n,
            errors=sum(v["global"]["shards"] for v in object_holes.values())
            + (0 if checks_ok else 1),
        )
        if self.resume_from:
            res.extra["resume"] = {
                "from": resume_path,  # the file THIS process read
                "objects_skipped": start_k,
                "prior_bytes": prior_bytes,  # cumulative across prior runs
                "prior_found": prior is not None,
            }
        res.extra.update(
            {
                "objects": self.n_objects,
                "objects_this_run": self.n_objects - start_k,
                "fetch_seconds": fetch_s,
                "stage_seconds": stage_s,
                "gather_seconds": gather_s,
                # >1.0 means fetch genuinely overlapped device work.
                "overlap_efficiency": (fetch_s + device_s) / wall if wall > 0 else 0.0,
                "verified": checks_ok if self.verify else None,
                "object_checksums": object_checksums if self.verify else None,
                # Distinct key from pod_ingest's flat extra["holes"]: this is
                # object-indexed; leaf shape {"shards", "bytes"} is shared.
                "holes_by_object": {str(k): v for k, v in object_holes.items()},
            }
        )
        if cloud_exp is not None:
            res.extra["metrics_export"] = cloud_exp.summary(cloud_periodic)
        if tel_summary is not None:
            res.extra["telemetry"] = tel_summary
        if flight is not None:
            res.extra["flight"] = flight.summary()
            if flight_path:
                res.extra["flight_journal"] = flight_path
        return res


def run_pod_ingest_stream(
    cfg: BenchConfig,
    n_objects: int,
    backend: Optional[StorageBackend] = None,
    verify: bool = False,
    snapshot_path: Optional[str] = None,
    resume_from: Optional[str] = None,
) -> RunResult:
    owns = backend is None
    backend = backend or open_backend(cfg)
    try:
        return StreamedPodIngest(
            cfg, backend, n_objects, verify=verify,
            snapshot_path=snapshot_path, resume_from=resume_from,
        ).run()
    finally:
        if owns:
            backend.close()
