"""``tpubench preflight`` — validate the run environment BEFORE burning a
benchmark window.

The reference's de-facto validation is "run it against real infrastructure
and see" (`/root/reference/README.md:4-9`, `execute_pb.sh:3-9`: a GCP VM,
a bucket, credentials, and optionally a DirectPath-eligible network). One
shot here checks each precondition separately and prints the env the run
would use, so a misconfiguration costs seconds, not a benchmark slot:

* **auth** — resolve the token source the config implies (service-account
  key / ADC / anonymous-for-custom-endpoint) and actually mint a token;
* **bucket** — open the configured backend and list it (auth + network +
  permission in one probe);
* **directpath** — eligibility screen for the gRPC DirectPath path: grpc
  importable, default endpoint, AND the GCE metadata server reachable
  (off-GCP the google-c2p resolver can never pick DirectPath backends);
* **native engine** — the C++ engine builds/loads, TLS availability;
* **env echo** — the exact endpoint/protocol/credential env the run
  would execute with.

Each check reports ``{name, ok, skipped?, detail}``; overall ``ok`` is the
AND of non-skipped checks. Exit code 1 on any failure (CLI).
"""

from __future__ import annotations

import os
import socket
import threading
from typing import Callable

from tpubench.config import BenchConfig

_ENV_KEYS = (
    "GOOGLE_APPLICATION_CREDENTIALS",
    "GOOGLE_CLOUD_ENABLE_DIRECT_PATH_XDS",
    "JAX_PLATFORMS",
    "XLA_FLAGS",
    "TPUBENCH_NUM_PROCESSES",
    "TPUBENCH_PROCESS_ID",
    "TPUBENCH_COORDINATOR",
)

_METADATA_HOST = "metadata.google.internal"


def _check(name: str, ok: bool, detail: str, skipped: bool = False) -> dict:
    return {"name": name, "ok": ok, "skipped": skipped, "detail": detail}


def _bounded(name: str, fn: Callable[[], dict], timeout_s: float) -> dict:
    """Run a probe with a HARD wall-clock bound: preflight exists to fail
    in seconds, and several failure modes (zero-egress DNS lookups, TCP
    connects to unreachable networks) hang far past any library timeout —
    getaddrinfo has none at all. A plain DAEMON thread, not a
    ThreadPoolExecutor: executor workers are non-daemon and joined at
    interpreter exit, so one hung resolver would block process shutdown
    long after the probe was reported failed."""
    box: dict = {}

    def _run() -> None:
        try:
            box["result"] = fn()
        except Exception as e:  # noqa: BLE001
            box["error"] = str(e)

    t = threading.Thread(target=_run, daemon=True, name=f"preflight-{name}")
    t.start()
    t.join(timeout=timeout_s)
    if t.is_alive():
        return _check(
            name, False,
            f"probe exceeded {timeout_s:.0f}s (network unreachable or "
            "hanging resolver)",
        )
    if "error" in box:
        return _check(name, False, f"probe raised: {box['error']}")
    return box["result"]


def _auth_check(cfg: BenchConfig) -> dict:
    from tpubench.storage.auth import (
        AnonymousTokenSource,
        make_token_source,
    )

    t = cfg.transport
    if t.protocol in ("fake", "local"):
        return _check(
            "auth", True,
            f"protocol {t.protocol!r} needs no credentials", skipped=True,
        )
    try:
        src = make_token_source(t.key_file, t.endpoint)
    except Exception as e:  # noqa: BLE001 — bad key file, no ADC
        return _check("auth", False, f"token source construction: {e}")
    if isinstance(src, AnonymousTokenSource):
        return _check(
            "auth", True,
            f"custom endpoint {t.endpoint!r}: anonymous (no Authorization "
            "header) — hermetic/fake-server mode",
        )
    try:
        tok = src.token()
    except Exception as e:  # noqa: BLE001 — refresh failure
        return _check("auth", False, f"token refresh failed: {e}")
    if not tok:
        return _check("auth", False, "token source returned no token")
    kind = "service-account key" if t.key_file else "ADC"
    return _check("auth", True, f"{kind} minted a bearer token (not shown)")


def _bucket_check(cfg: BenchConfig) -> dict:
    from tpubench.storage import open_backend

    w = cfg.workload
    if cfg.transport.protocol == "fake":
        # In-process backend: nothing to reach (and constructing it
        # prepopulates workers × object_size of deterministic bytes —
        # gigabytes under the reference-default config).
        return _check(
            "bucket", True, "in-process fake backend: always reachable",
            skipped=True,
        )
    try:
        backend = open_backend(cfg)
    except Exception as e:  # noqa: BLE001
        return _check("bucket", False, f"backend construction: {e}")
    try:
        objs = backend.list(w.object_name_prefix)
        return _check(
            "bucket", True,
            f"list({w.object_name_prefix!r}) on {w.bucket!r}: "
            f"{len(objs)} object(s) visible",
        )
    except Exception as e:  # noqa: BLE001 — 403/404/network
        return _check(
            "bucket", False, f"list on {w.bucket!r} failed: {e}"
        )
    finally:
        backend.close()


def _metadata_server_reachable(timeout_s: float = 0.6) -> bool:
    try:
        with socket.create_connection((_METADATA_HOST, 80), timeout=timeout_s):
            return True
    except OSError:
        return False


def _directpath_check(cfg: BenchConfig) -> dict:
    t = cfg.transport
    if t.protocol != "grpc" or not t.directpath:
        return _check(
            "directpath", True,
            "not requested (protocol!=grpc or transport.directpath=False)",
            skipped=True,
        )
    try:
        import grpc  # noqa: F401
    except Exception as e:  # noqa: BLE001
        return _check("directpath", False, f"grpcio unavailable: {e}")
    default_ep = not t.endpoint or "googleapis.com" in t.endpoint
    if not default_ep:
        return _check(
            "directpath", False,
            f"custom endpoint {t.endpoint!r}: the google-c2p resolver "
            "only applies to the default endpoint (gcs_grpc rejects this "
            "loudly at run time)",
        )
    if not _metadata_server_reachable():
        return _check(
            "directpath", False,
            f"GCE metadata server ({_METADATA_HOST}) unreachable: not a "
            "GCP VM, DirectPath backends cannot be selected",
        )
    xds = os.environ.get("GOOGLE_CLOUD_ENABLE_DIRECT_PATH_XDS", "")
    return _check(
        "directpath", True,
        "on-GCP (metadata server reachable); google-c2p resolver will "
        f"probe eligibility at channel build (DIRECT_PATH_XDS={xds!r})",
    )


def _engine_check(cfg: BenchConfig) -> dict:
    need = (
        cfg.transport.native_receive
        or cfg.transport.http2
        or cfg.workload.fetch_executor.startswith("native")
    )
    err = ""
    try:
        from tpubench.native.engine import get_engine

        eng = get_engine()
    except Exception as e:  # noqa: BLE001
        eng = None
        err = str(e)
    if eng is None:
        detail = "native engine unavailable" + (f": {err}" if err else "")
        return _check("native_engine", not need, detail, skipped=not need)
    return _check(
        "native_engine", True,
        f"engine loaded (tls={'yes' if eng.tls_available() else 'no'})",
    )


def _executor_check(cfg: BenchConfig) -> dict:
    """Honest-executor preflight (satellite of the reactor-completeness
    work): when the config requests the reactor, predict whether it will
    actually engage. An honest fallback under plain ``native`` gets the
    one-line counted warning HERE, before any benchmark runs; a pinned
    ``native-reactor`` that cannot engage is a preflight FAIL."""
    fe = cfg.workload.fetch_executor
    if not fe.startswith("native"):
        return _check(
            "fetch_executor", True, f"python orchestration path ({fe})",
            skipped=True,
        )
    from tpubench.workloads.fetch_executor import executor_mode, warn_fallback

    try:
        from tpubench.native.engine import get_engine

        eng = get_engine()
    except Exception:  # noqa: BLE001
        eng = None
    if eng is None:
        # the native_engine check already reports the load failure
        return _check("fetch_executor", True, "see native_engine",
                      skipped=True)
    if executor_mode(fe) == "threads":
        return _check("fetch_executor", True, "legacy thread pool (pinned)")
    reason = ""
    if not getattr(eng, "_has_pool_create2", False):
        reason = "stale libtpubench.so without the reactor symbols"
    else:
        endpoint = cfg.transport.endpoint or "https://storage.googleapis.com"
        if endpoint.startswith("https") and not eng.tls_available():
            reason = "https endpoint but OpenSSL did not load"
    if not reason:
        return _check("fetch_executor", True, f"reactor engages ({fe})")
    if fe == "native-reactor":
        return _check(
            "fetch_executor", False,
            f"pinned native-reactor cannot engage: {reason}",
        )
    warn_fallback("reactor", "threads", reason)
    return _check(
        "fetch_executor", True,
        f"requested reactor will fall back to legacy ({reason})",
    )


def run_preflight(cfg: BenchConfig, probe_timeout_s: float = 15.0) -> dict:
    checks = [
        _bounded("auth", lambda: _auth_check(cfg), probe_timeout_s),
        _bounded("bucket", lambda: _bucket_check(cfg), probe_timeout_s),
        _bounded("directpath", lambda: _directpath_check(cfg), probe_timeout_s),
        _engine_check(cfg),
        _executor_check(cfg),
    ]
    t = cfg.transport
    endpoint = t.endpoint or (
        "https://storage.googleapis.com" if t.protocol == "http"
        else "storage.googleapis.com:443" if t.protocol == "grpc"
        else "(in-process)"
    )
    env = {
        "protocol": t.protocol,
        "endpoint": endpoint,
        "bucket": cfg.workload.bucket,
        "object_name_prefix": cfg.workload.object_name_prefix,
        "http2": t.http2,
        "native_receive": t.native_receive,
        "directpath": t.directpath,
        "fetch_executor": cfg.workload.fetch_executor,
        "key_file": t.key_file or "(ADC)",
        "env": {k: os.environ.get(k, "") for k in _ENV_KEYS},
    }
    ok = all(c["ok"] for c in checks if not c["skipped"])
    return {"ok": ok, "checks": checks, "effective": env}


def format_preflight(result: dict) -> str:
    lines = []
    for c in result["checks"]:
        mark = "SKIP" if c["skipped"] else ("ok " if c["ok"] else "FAIL")
        lines.append(f"[{mark}] {c['name']}: {c['detail']}")
    e = result["effective"]
    lines.append(
        f"run would use: protocol={e['protocol']} endpoint={e['endpoint']} "
        f"bucket={e['bucket']} prefix={e['object_name_prefix']} "
        f"http2={e['http2']} native_receive={e['native_receive']} "
        f"directpath={e['directpath']} executor={e['fetch_executor']} "
        f"creds={e['key_file']}"
    )
    for k, v in e["env"].items():
        if v:
            lines.append(f"  {k}={v}")
    lines.append("preflight: " + ("OK" if result["ok"] else "FAILED"))
    return "\n".join(lines)
