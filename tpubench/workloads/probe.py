"""Host→HBM transfer-physics probe (``tpubench probe``).

No reference analog — this exists because benchmark numbers on shared or
shaped transfer paths are uninterpretable without the path's physics. One
command characterizes the device transfer tunnel:

* **per-transfer fixed cost** — small (2 MB) vs mid (8 MB) sync transfer
  rates separate per-call overhead from streaming bandwidth (why the
  staging pipeline aggregates granules into slots);
* **size sweep** — sync ``device_put`` bandwidth at several transfer
  sizes, all measured in positionally identical cycles;
* **burst/floor detection** — N identical ramp→measure→sleep cycles of
  one fixed size; on a shaped tunnel the samples are bimodal (a fast
  state for the first few hundred MB after idle, then a hard floor), so
  the probe reports every sample plus peak/median/floor;
* **slow-start** — the first transfer after an idle gap vs after a ramp.

The output JSON is exactly the evidence ``bench.py``'s measurement
protocol is built on (frontload key measurements into the granted fast
window; medians across cycles are shaping noise, not config signal).
"""

from __future__ import annotations

import statistics
import time
from typing import Optional

import numpy as np

from tpubench.config import MB, BenchConfig
from tpubench.metrics.report import RunResult


def _mk(size: int) -> np.ndarray:
    rng = np.random.default_rng(seed=size)
    return rng.integers(0, 255, size=(size // 128, 128), dtype=np.uint8)


def analyze_sweep(sweep: dict[str, float]) -> tuple[list[str], Optional[float]]:
    """Anomaly screen over the size-sweep cells (pure, test-injectable).

    A cell measuring < 1/3 of the sweep's best cell hit a stall or the
    shaped floor mid-sweep — deriving per-transfer fixed-cost physics
    from it would present a budget artifact as physics (round-4: the 2 MB
    cell measured 0.13 GB/s on a drained budget and
    ``fixed_cost_speedup`` was computed from it anyway). The smallest
    (2 MB) cell gets a looser 1/6 threshold: per-transfer fixed cost
    legitimately halves small-transfer throughput (that deficit IS the
    physics this sweep exists to measure), but a >6x deficit is beyond
    plausible fixed cost — a stall. Returns (anomalous_cells,
    fixed_cost_speedup_8MB_over_2MB or None when either input cell is
    anomalous/missing)."""
    vals = [v for v in sweep.values() if v > 0]
    if not vals:
        return list(sweep.keys()), None
    best = max(vals)

    def _thresh(k: str) -> float:
        return best / 6 if k == "2MB" else best / 3

    anomalies = [k for k, v in sweep.items() if v <= 0 or v < _thresh(k)]
    fixed_cost = None
    if (
        sweep.get("2MB")
        and sweep.get("8MB")
        and "2MB" not in anomalies
        and "8MB" not in anomalies
    ):
        fixed_cost = sweep["8MB"] / sweep["2MB"]
    return anomalies, fixed_cost


def _put_rate(dev, arr: np.ndarray, reps: int) -> float:
    import jax

    t0 = time.perf_counter()
    for _ in range(reps):
        jax.device_put(arr, dev).block_until_ready()
    dt = time.perf_counter() - t0
    return reps * arr.nbytes / 1e9 / dt if dt > 0 else 0.0


def run_probe(cfg: BenchConfig, cycles: int = 8, sleep_s: float = 2.0) -> RunResult:
    import jax

    dev = jax.local_devices()[0]
    warm = _mk(8 * MB)

    def ramp(n: int = 3) -> None:
        for _ in range(n):
            jax.device_put(warm, dev).block_until_ready()

    t_start = time.perf_counter()
    total = 0

    # Slow-start: first put after this process's idle (nothing sent yet)
    # vs after a ramp.
    cold_first = _put_rate(dev, warm, 1)
    ramp(3)
    warm_first = _put_rate(dev, warm, 1)
    total += 5 * warm.nbytes

    # Size sweep in positionally identical cycles (ramp → measure),
    # run back-to-back inside whatever fast window remains.
    sweep: dict[str, float] = {}
    for size_mb, reps in ((2, 8), (8, 4), (16, 2), (32, 1)):
        arr = _mk(size_mb * MB)
        ramp(1)
        sweep[f"{size_mb}MB"] = round(_put_rate(dev, arr, reps), 4)
        total += warm.nbytes + reps * arr.nbytes

    # Burst/floor: identical ramp → measure → sleep cycles of one fixed
    # shape. Bimodal samples = external shaping; flat samples = a real
    # sustained ceiling.
    arr = _mk(16 * MB)
    samples: list[float] = []
    for i in range(max(1, cycles)):
        if i:
            time.sleep(sleep_s)  # idle gap between cycles, none after last
        ramp(2)
        samples.append(round(_put_rate(dev, arr, 2), 4))
        total += 2 * warm.nbytes + 2 * arr.nbytes
    wall = time.perf_counter() - t_start

    peak = max(samples)
    floor = min(samples)
    med = statistics.median(samples)
    # Shaped = large spread AND the slow state persists (median near the
    # floor): a single transient stall depresses one sample but not the
    # median, so it does not flip the verdict.
    shaped = peak > 3 * floor and med < peak / 2
    sweep_anomalies, fixed_cost_ratio = analyze_sweep(sweep)
    # A cold-first sample FASTER than post-ramp is backwards (ramping
    # should help, not hurt): the classic signature of the budget
    # draining between the two measurements — flag it rather than
    # presenting it as slow-start physics (round-4: 4.39 cold vs 1.75
    # post-ramp went unflagged).
    slow_start_anomalous = cold_first > 1.5 * warm_first

    res = RunResult(
        workload="probe",
        config=cfg.to_dict(),
        bytes_total=total,
        wall_seconds=wall,
        gbps=peak,
        gbps_per_chip=peak,  # one device under probe
        n_chips=1,
        summaries={},
    )
    res.extra.update(
        {
            "device": str(dev),
            "slow_start": {
                "cold_first_gbps": round(cold_first, 4),
                "post_ramp_gbps": round(warm_first, 4),
                "anomalous": slow_start_anomalous,
            },
            "size_sweep_gbps": sweep,
            "sweep_anomalies": sweep_anomalies,
            "fixed_cost_speedup_8MB_over_2MB": (
                round(fixed_cost_ratio, 3)
                if fixed_cost_ratio is not None
                else None
            ),
            "cycle_samples_gbps": samples,
            "peak_gbps": round(peak, 4),
            "median_gbps": round(med, 4),
            "floor_gbps": round(floor, 4),
            "shaped": shaped,
            "note": (
                "shaped=True means peak > 3x floor across identical "
                "cycles: the transfer path grants a fast window then "
                "shapes to a floor — report peaks with the floor "
                "disclosed, and never average across cycles."
            ),
        }
    )
    return res
