"""Root read benchmark — the reference's flagship (``main.go``).

Reproduces the §3.1 call stack TPU-first:

* ``--worker`` threads, worker ``i`` owns object ``<prefix><i>``
  (``main.go:121``), each doing ``--read-call-per-worker`` full-object reads;
* per read: span → open reader → stream through a reused granule buffer
  (2 MB default, tuned to the gRPC server's message chunking,
  ``main.go:123-125``) → record full-read latency (``main.go:133,145-146``)
  → close (``main.go:148``);
* errgroup join semantics (``main.go:200-219``) via :class:`WorkerGroup`.

Deltas over the reference (the north star):

* bytes can be *staged to TPU HBM* per granule via a ``sink_factory`` hook
  (see :mod:`tpubench.staging`) instead of discarded into host RAM
  (``io.Discard``, main.go:140);
* first-byte latency is recorded as its own histogram;
* per-worker byte counts and latency buffers — no shared mutable hot-loop
  state (the reference's ssd_test races on exactly this).
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass
from typing import Callable, Optional, Protocol

from tpubench.config import BenchConfig
from tpubench.metrics import MetricSet
from tpubench.metrics.report import RunResult
from tpubench.obs.flight import (
    flight_from_config,
    host_journal_path,
    transport_label,
)
from tpubench.obs.tracing import NoopTracer, Tracer
from tpubench.storage import open_backend
from tpubench.storage.base import (
    StorageBackend,
    read_object_into_sink,
    read_object_through,
)
from tpubench.workloads.common import ElasticGate, WorkerGroup


class Sink(Protocol):
    """Per-worker granule consumer (the staging hook).

    Sinks may additionally implement the zero-copy pair
    ``acquire() -> memoryview`` / ``commit(n: int)`` (see
    :class:`ZeroCopySink`); the workload routes through it only when BOTH
    methods are present, and records which route ran in the result extras
    (``staging_zero_copy``) so a silently degraded sink is visible in
    reports.
    """

    def submit(self, mv: memoryview) -> None: ...

    def finish(self) -> dict: ...


class ZeroCopySink(Sink, Protocol):
    """Sink whose staging slots the fetch path fills in place."""

    def acquire(self) -> memoryview: ...

    def commit(self, n: int) -> None: ...


SinkFactory = Callable[[int], Sink]


def _build_read_controller(cfg, read_recorders, bytes_fn, backend, gate,
                           flight, stager_registry=None):
    """Tune controller for the Python read path: live knobs are the
    elastic worker fan-out, (when hedging is on) the hedge delay, and
    (when staging overlaps) the staging executor's in-flight depth —
    fanned out to every worker's ring through the stager registry;
    goodput/p99 sampled off the run's own per-worker recorders."""
    from tpubench.storage.tail import HedgedBackend, find_tail_layer
    from tpubench.tune.controller import (
        Knob,
        RecorderSampler,
        TuneController,
        hedge_delay_knob,
        staging_depth_ceiling,
    )

    wanted = set(cfg.tune.knobs)
    knobs = []
    if "workers" in wanted and gate.total > 1:
        knobs.append(Knob(
            "workers", gate.active, gate.set_active,
            lo=1, hi=gate.total, mode="mul",
        ))
    if "hedge_delay_s" in wanted and cfg.transport.tail.hedge:
        hb = find_tail_layer(backend, HedgedBackend)
        if hb is not None:
            knobs.append(hedge_delay_knob(
                cfg.transport.tail.hedge_delay_s, hb.set_hedge_delay,
            ))
    if stager_registry is not None:
        depth0 = max(1, cfg.staging.depth)
        knobs.append(Knob(
            "staging_depth", depth0, stager_registry.set_depth,
            lo=1, hi=staging_depth_ceiling(depth0), mode="mul",
        ))
    if not knobs:
        return None
    sampler = RecorderSampler(read_recorders, bytes_fn)
    ring = flight.worker("tune") if flight is not None else None
    return TuneController(cfg.tune, knobs, sampler, flight_ring=ring)


@dataclass
class ReadWorkload:
    cfg: BenchConfig
    backend: StorageBackend
    tracer: Tracer
    sink_factory: Optional[SinkFactory] = None

    def run(self) -> RunResult:
        w = self.cfg.workload
        n = w.workers
        metrics = MetricSet()
        recorders = [metrics.new_worker(f"w{i}") for i in range(n)]
        worker_bytes = [0] * n
        sink_stats: list[dict] = [{} for _ in range(n)]
        zero_copy_used = [False] * n
        # Flight recorder (obs/flight.py): per-worker record rings, one
        # structured phase record per read — same worker-owned-array
        # race-freedom as the latency recorders above.
        flight = flight_from_config(self.cfg)
        tlabel = transport_label(self.cfg)
        flights = [
            flight.worker(f"w{i}") if flight is not None else None
            for i in range(n)
        ]
        # Native transport counters (tb_stats_*): delta across the run is
        # folded into the result/journal when the engine is live.
        from tpubench.native.engine import peek_engine

        eng0 = peek_engine()
        native_stats0 = eng0.stats() if eng0 is not None else {}

        # Live telemetry (obs/telemetry.py): registry fed record-by-record
        # off the flight tap, read latency sampled incrementally off the
        # per-worker recorders, journal streamed each tick for `top`.
        from tpubench.obs.telemetry import telemetry_from_config

        jpath_stream = None
        if self.cfg.obs.flight_journal:
            d = self.cfg.dist
            jpath_stream = host_journal_path(
                self.cfg.obs.flight_journal, d.process_id, d.num_processes
            )
        tel = telemetry_from_config(self.cfg)
        tel_summary = None
        if tel is not None:
            tel.resource["workload"] = "read"
            if flight is not None:
                tel.attach_flight(flight)
                if jpath_stream:
                    tel.stream_journal(
                        flight, jpath_stream,
                        extra_fn=lambda: {"workload": "read"},
                        max_bytes=self.cfg.obs.journal_max_bytes,
                    )
            tel.attach_recorders(metrics.read_latency)
            tel.start()

        # Adaptive tuning (tpubench/tune/): an elastic gate makes worker
        # fan-out a LIVE knob — all threads spawn, the controller admits
        # a subset; parked workers resume when it grows the pool back.
        tune_on = getattr(self.cfg, "tune", None) is not None and \
            self.cfg.tune.enabled
        gate = ElasticGate(n, n) if tune_on else None
        # Overlapped-staging depth as a live knob: workers build their
        # stagers lazily inside the threads, so the controller actuates a
        # registry that fans set_depth out to every attached ring (and
        # replays the commanded depth onto late attachers).
        stager_registry = None
        sink_factory = self.sink_factory
        if (
            tune_on and sink_factory is not None
            and "staging_depth" in self.cfg.tune.knobs
            and self.cfg.staging.mode == "device_put"
            and self.cfg.staging.double_buffer
            and self.cfg.staging.depth > 1
            and not self.cfg.staging.validate_checksum
        ):
            from tpubench.staging.executor import StagerRegistry

            stager_registry = StagerRegistry()
            base_factory = sink_factory
            sink_factory = lambda i: stager_registry.attach(base_factory(i))  # noqa: E731

        def worker(i: int, cancel) -> None:
            read_rec, fb_rec = recorders[i]
            wf = flights[i]
            name = f"{w.object_name_prefix}{i}"  # main.go:121
            sink = sink_factory(i) if sink_factory else None
            # Zero-copy route: fetch lands bytes directly in the staging
            # slot (sink.acquire/commit); otherwise stream through a reused
            # per-worker granule buffer (main.go:125) with optional copying
            # submit.
            zero_copy = (
                sink is not None
                and self.cfg.staging.zero_copy
                and hasattr(sink, "acquire")
                and hasattr(sink, "commit")
            )
            zero_copy_used[i] = zero_copy
            granule = (
                None if zero_copy else memoryview(bytearray(w.granule_bytes))
            )
            submit = sink.submit if (sink and not zero_copy) else None
            total_local = 0
            try:
                for _ in range(w.read_calls_per_worker):
                    if cancel.is_set():
                        break
                    if gate is not None and not gate.admit(i, cancel):
                        break
                    with self.tracer.span(
                        "ReadObject", bucket=w.bucket, object=name
                    ) as span:
                        t0 = time.perf_counter_ns()
                        # The op begins INSIDE the tracer span's scope,
                        # so its flight record joins the span's trace
                        # (RecordingTracer/OTel install a TraceContext)
                        # — the journal and the exported spans tell one
                        # stitched story per read.
                        op = (
                            wf.begin(name, tlabel, enqueue_ns=t0)
                            if wf is not None else None
                        )
                        if op is not None:
                            # Bidirectional handle: the exported span
                            # carries the journal record's identity.
                            span.event(
                                "trace_context", trace_id=op.trace_id,
                                span_id=op.span_id,
                            )
                        try:
                            reader = self.backend.open_read(name)
                            if zero_copy:
                                nbytes, fb_ns = read_object_into_sink(
                                    reader, sink, w.granule_bytes
                                )
                            else:
                                nbytes, fb_ns = read_object_through(
                                    reader, granule, submit
                                )
                            t1 = time.perf_counter_ns()
                        except BaseException as e:
                            if op is not None:
                                op.finish(error=e)
                            raise
                        read_rec.record_ns(t1 - t0)
                        if fb_ns is not None:
                            fb_rec.record_ns(fb_ns - t0)
                            span.event("first_byte")
                        if op is not None:
                            if fb_ns is not None:
                                op.mark("first_byte", fb_ns)
                            op.mark("body_complete", t1)
                            op.finish(nbytes)
                        total_local += nbytes
                        # Single-writer slot: the periodic exporter reads a
                        # live pod-progress sum without shared hot-loop state.
                        worker_bytes[i] = total_local
            finally:
                if sink is not None:
                    sink_stats[i] = sink.finish() or {}
                worker_bytes[i] = total_local

        from tpubench.obs.exporters import metrics_session_from_config

        session = metrics_session_from_config(
            self.cfg, metrics, bytes_fn=lambda: sum(worker_bytes)
        )
        metrics.ingest.start()
        group = WorkerGroup(abort_on_error=w.abort_on_error)
        result_errors = 0
        controller = None
        duration_timer = None
        if gate is not None:
            controller = _build_read_controller(
                self.cfg, metrics.read_latency,
                lambda: sum(worker_bytes), self.backend, gate, flight,
                stager_registry=stager_registry,
            )
            # Online read sessions are duration-bounded: a shrink parks
            # workers with reads remaining, so read-count completion can
            # no longer be the only exit. No controller (nothing
            # actuatable) = no cap — the run must not silently truncate.
            if controller is not None and self.cfg.tune.duration_s > 0:
                import threading as _threading

                duration_timer = _threading.Timer(
                    self.cfg.tune.duration_s, group.cancel.set
                )
                duration_timer.daemon = True
        try:
            if session is not None:
                session.__enter__()
            try:
                # Ambient flight recorder: the staging slot pipeline
                # (constructed inside the workers) attaches its per-slot
                # hbm_staged records to the same journal.
                with (flight.activate() if flight is not None
                      else contextlib.nullcontext()):
                    if controller is not None:
                        controller.start()
                    if duration_timer is not None:
                        duration_timer.start()
                    gres = group.run(n, worker, name="read")
                result_errors = gres.error_count
            finally:
                if duration_timer is not None:
                    duration_timer.cancel()
                tune_stats = (
                    controller.stop() if controller is not None else None
                )
                metrics.ingest.stop()
                metrics.ingest.bytes = sum(worker_bytes)
                # Stage-latency recorders created by sinks live in their
                # stats; merge BEFORE the session's final flush so the
                # exported stage_latency histogram isn't silently empty.
                for st in sink_stats:
                    rec = st.get("stage_recorder")
                    if rec is not None:
                        metrics.stage_latency.append(rec)
        finally:
            if session is not None:
                # Guaranteed final flush — now with complete counters.
                session.__exit__(None, None, None)
            if tel is not None:
                # Workers have joined and every sink finished: the tapped
                # record set is final. Closed in the finally so the HTTP
                # server and tick thread never outlive a failed run.
                from tpubench.staging.stats import staging_extra as _sx

                _blk = _sx(sink_stats)
                tel.set_chips(
                    int(sink_stats[0].get("n_chips", 1) or 1)
                    if sink_stats else 1
                )
                tel_summary = tel.close(
                    final_extra={"staging": _blk} if _blk else None
                )

        wall = metrics.ingest.seconds
        gbps = metrics.ingest.gbps()
        n_chips = max(1, int(sink_stats[0].get("n_chips", 1))) if sink_stats else 1
        staged = sum(int(st.get("staged_bytes", 0)) for st in sink_stats)
        res = RunResult(
            workload="read",
            config=self.cfg.to_dict(),
            bytes_total=metrics.ingest.bytes,
            wall_seconds=wall,
            gbps=gbps,
            gbps_per_chip=gbps / n_chips,
            n_chips=n_chips,
            summaries=metrics.summaries(),
            errors=result_errors,
        )
        if session is not None:
            res.extra["metrics_export"] = session.summary()
        if tel_summary is not None:
            res.extra["telemetry"] = tel_summary
        if tune_stats is not None:
            res.extra["tune"] = tune_stats
        # Native-receive connection accounting (connects/reuses/
        # stale_retries) — read from the pool only if one was actually
        # built, so this never constructs a pool as a side effect.
        inner = self.backend
        for _ in range(8):  # unwrap retry/tail decorators to the base client
            nxt = getattr(inner, "inner", None)
            if nxt is None:
                break
            inner = nxt
        native_pool = getattr(inner, "_native_pool_obj", None)
        if native_pool is not None:
            res.extra["native_conn_stats"] = dict(native_pool.stats)
        # Tail-tolerance counters (hedge wins/losses/wasted bytes, stalls,
        # breaker state/open-time) from whatever tail wrappers are in the
        # backend chain — the resilience scorecard's raw material.
        from tpubench.storage.tail import collect_tail_stats

        tail_stats = collect_tail_stats(self.backend)
        if tail_stats:
            res.extra["tail"] = tail_stats
        if staged:
            res.extra["staging_zero_copy"] = all(zero_copy_used)
            res.extra["staged_bytes"] = staged
            res.extra["staged_gbps"] = (staged / 1e9) / wall if wall > 0 else 0.0
            res.extra["staged_gbps_per_chip"] = res.extra["staged_gbps"] / n_chips
            # Phase breakdown (averaged per worker, seconds): how much of
            # the wall the fetch threads spent blocked on transfers vs in
            # device_put submission — the rest is fetch + pipeline
            # overhead. Feeds the bench's gap root-cause fields.
            live = [st for st in sink_stats if "transfer_wait_ns" in st]
            if live:
                k = len(live)
                res.extra["staging_breakdown"] = {
                    "workers": k,
                    # put_submit semantics differ by drain mode (drainer
                    # time is CONCURRENT with fetch) — consumers branch.
                    "drain": live[0].get("drain", "inline"),
                    "wall_s": wall,
                    "transfer_wait_s": sum(
                        st["transfer_wait_ns"] for st in live
                    ) / 1e9 / k,
                    "put_submit_s": sum(
                        st["put_submit_ns"] for st in live
                    ) / 1e9 / k,
                }
                if any("checksum_reduce_ns" in st for st in live):
                    res.extra["staging_breakdown"]["checksum_reduce_s"] = sum(
                        st.get("checksum_reduce_ns", 0) for st in live
                    ) / 1e9 / k
            # Overlap story (extra["staging"]): depth, transfers-in-flight
            # gauge, transfer wait vs flight, pooled staging_efficiency.
            from tpubench.staging.stats import staging_extra

            staging_block = staging_extra(sink_stats)
            if staging_block is not None:
                res.extra["staging"] = staging_block
        checks = [st["checksum_ok"] for st in sink_stats if "checksum_ok" in st]
        if checks:
            res.extra["checksum_ok"] = all(checks)
        # Flight recorder: phase-breakdown summary stamped into the result
        # (so BENCH trajectories carry per-phase p50/p99), native transport
        # counter deltas folded in, per-host journal written when asked.
        eng1 = peek_engine()
        native_delta = None
        if eng1 is not None:
            stats1 = eng1.stats()
            native_delta = {
                k: v - native_stats0.get(k, 0) for k, v in stats1.items()
            }
            if any(native_delta.values()):
                res.extra["native_transport"] = native_delta
        if flight is not None:
            res.extra["flight"] = flight.summary()
            if jpath_stream:
                extra = {"workload": "read", "n_chips": n_chips}
                if native_delta:
                    extra["native_transport"] = native_delta
                res.extra["flight_journal"] = flight.write_journal(
                    jpath_stream, extra=extra,
                    max_bytes=self.cfg.obs.journal_max_bytes,
                )
        return res


def run_read(
    cfg: BenchConfig,
    backend: Optional[StorageBackend] = None,
    tracer: Optional[Tracer] = None,
    sink_factory: Optional[SinkFactory] = None,
) -> RunResult:
    owns_backend = backend is None
    tracer = tracer or NoopTracer()
    if getattr(cfg, "coop", None) is not None and cfg.coop.enabled:
        # The cooperative cache lives in the pipeline miss path, which
        # only train-ingest drives — say so instead of silently running
        # the plain per-host read (every other knob either wires in or
        # rejects; a quiet no-op would poison an A/B).
        import sys

        print(
            "read: --coop has no effect on this workload (the "
            "cooperative cache rides the train-ingest pipeline miss "
            "path)", file=sys.stderr,
        )
    # The backend gets the same tracer: its per-request spans nest under
    # the workload's ReadObject spans (OC-bridge analog).
    backend = backend or open_backend(cfg, tracer=tracer)
    try:
        if cfg.workload.fetch_executor.startswith("native"):
            from tpubench.workloads.fetch_executor import (
                run_read_native_executor,
                run_read_native_staged,
            )

            if cfg.staging.mode == "none":
                return run_read_native_executor(cfg, backend)
            return run_read_native_staged(cfg, backend)
        return ReadWorkload(
            cfg=cfg,
            backend=backend,
            tracer=tracer,
            sink_factory=sink_factory,
        ).run()
    finally:
        if owns_backend:
            backend.close()
