"""Root read benchmark — the reference's flagship (``main.go``).

Reproduces the §3.1 call stack TPU-first:

* ``--worker`` threads, worker ``i`` owns object ``<prefix><i>``
  (``main.go:121``), each doing ``--read-call-per-worker`` full-object reads;
* per read: span → open reader → stream through a reused granule buffer
  (2 MB default, tuned to the gRPC server's message chunking,
  ``main.go:123-125``) → record full-read latency (``main.go:133,145-146``)
  → close (``main.go:148``);
* errgroup join semantics (``main.go:200-219``) via :class:`WorkerGroup`.

Deltas over the reference (the north star):

* bytes can be *staged to TPU HBM* per granule via a ``sink_factory`` hook
  (see :mod:`tpubench.staging`) instead of discarded into host RAM
  (``io.Discard``, main.go:140);
* first-byte latency is recorded as its own histogram;
* per-worker byte counts and latency buffers — no shared mutable hot-loop
  state (the reference's ssd_test races on exactly this).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Optional, Protocol

from tpubench.config import BenchConfig
from tpubench.metrics import MetricSet
from tpubench.metrics.report import RunResult
from tpubench.obs.tracing import NoopTracer, Tracer
from tpubench.storage import open_backend
from tpubench.storage.base import (
    StorageBackend,
    read_object_into_sink,
    read_object_through,
)
from tpubench.workloads.common import WorkerGroup


class Sink(Protocol):
    """Per-worker granule consumer (the staging hook).

    Sinks may additionally implement the zero-copy pair
    ``acquire() -> memoryview`` / ``commit(n: int)`` (see
    :class:`ZeroCopySink`); the workload routes through it only when BOTH
    methods are present, and records which route ran in the result extras
    (``staging_zero_copy``) so a silently degraded sink is visible in
    reports.
    """

    def submit(self, mv: memoryview) -> None: ...

    def finish(self) -> dict: ...


class ZeroCopySink(Sink, Protocol):
    """Sink whose staging slots the fetch path fills in place."""

    def acquire(self) -> memoryview: ...

    def commit(self, n: int) -> None: ...


SinkFactory = Callable[[int], Sink]


@dataclass
class ReadWorkload:
    cfg: BenchConfig
    backend: StorageBackend
    tracer: Tracer
    sink_factory: Optional[SinkFactory] = None

    def run(self) -> RunResult:
        w = self.cfg.workload
        n = w.workers
        metrics = MetricSet()
        recorders = [metrics.new_worker(f"w{i}") for i in range(n)]
        worker_bytes = [0] * n
        sink_stats: list[dict] = [{} for _ in range(n)]
        zero_copy_used = [False] * n

        def worker(i: int, cancel) -> None:
            read_rec, fb_rec = recorders[i]
            name = f"{w.object_name_prefix}{i}"  # main.go:121
            sink = self.sink_factory(i) if self.sink_factory else None
            # Zero-copy route: fetch lands bytes directly in the staging
            # slot (sink.acquire/commit); otherwise stream through a reused
            # per-worker granule buffer (main.go:125) with optional copying
            # submit.
            zero_copy = (
                sink is not None
                and self.cfg.staging.zero_copy
                and hasattr(sink, "acquire")
                and hasattr(sink, "commit")
            )
            zero_copy_used[i] = zero_copy
            granule = (
                None if zero_copy else memoryview(bytearray(w.granule_bytes))
            )
            submit = sink.submit if (sink and not zero_copy) else None
            total_local = 0
            try:
                for _ in range(w.read_calls_per_worker):
                    if cancel.is_set():
                        break
                    with self.tracer.span(
                        "ReadObject", bucket=w.bucket, object=name
                    ) as span:
                        t0 = time.perf_counter_ns()
                        reader = self.backend.open_read(name)
                        if zero_copy:
                            nbytes, fb_ns = read_object_into_sink(
                                reader, sink, w.granule_bytes
                            )
                        else:
                            nbytes, fb_ns = read_object_through(
                                reader, granule, submit
                            )
                        t1 = time.perf_counter_ns()
                        read_rec.record_ns(t1 - t0)
                        if fb_ns is not None:
                            fb_rec.record_ns(fb_ns - t0)
                            span.event("first_byte")
                        total_local += nbytes
                        # Single-writer slot: the periodic exporter reads a
                        # live pod-progress sum without shared hot-loop state.
                        worker_bytes[i] = total_local
            finally:
                if sink is not None:
                    sink_stats[i] = sink.finish() or {}
                worker_bytes[i] = total_local

        from tpubench.obs.exporters import metrics_session_from_config

        session = metrics_session_from_config(
            self.cfg, metrics, bytes_fn=lambda: sum(worker_bytes)
        )
        metrics.ingest.start()
        group = WorkerGroup(abort_on_error=w.abort_on_error)
        result_errors = 0
        try:
            if session is not None:
                session.__enter__()
            try:
                gres = group.run(n, worker, name="read")
                result_errors = gres.error_count
            finally:
                metrics.ingest.stop()
                metrics.ingest.bytes = sum(worker_bytes)
                # Stage-latency recorders created by sinks live in their
                # stats; merge BEFORE the session's final flush so the
                # exported stage_latency histogram isn't silently empty.
                for st in sink_stats:
                    rec = st.get("stage_recorder")
                    if rec is not None:
                        metrics.stage_latency.append(rec)
        finally:
            if session is not None:
                # Guaranteed final flush — now with complete counters.
                session.__exit__(None, None, None)

        wall = metrics.ingest.seconds
        gbps = metrics.ingest.gbps()
        n_chips = max(1, int(sink_stats[0].get("n_chips", 1))) if sink_stats else 1
        staged = sum(int(st.get("staged_bytes", 0)) for st in sink_stats)
        res = RunResult(
            workload="read",
            config=self.cfg.to_dict(),
            bytes_total=metrics.ingest.bytes,
            wall_seconds=wall,
            gbps=gbps,
            gbps_per_chip=gbps / n_chips,
            n_chips=n_chips,
            summaries=metrics.summaries(),
            errors=result_errors,
        )
        if session is not None:
            res.extra["metrics_export"] = session.summary()
        # Native-receive connection accounting (connects/reuses/
        # stale_retries) — read from the pool only if one was actually
        # built, so this never constructs a pool as a side effect.
        inner = getattr(self.backend, "inner", self.backend)
        native_pool = getattr(inner, "_native_pool_obj", None)
        if native_pool is not None:
            res.extra["native_conn_stats"] = dict(native_pool.stats)
        if staged:
            res.extra["staging_zero_copy"] = all(zero_copy_used)
            res.extra["staged_bytes"] = staged
            res.extra["staged_gbps"] = (staged / 1e9) / wall if wall > 0 else 0.0
            res.extra["staged_gbps_per_chip"] = res.extra["staged_gbps"] / n_chips
        checks = [st["checksum_ok"] for st in sink_stats if "checksum_ok" in st]
        if checks:
            res.extra["checksum_ok"] = all(checks)
        return res


def run_read(
    cfg: BenchConfig,
    backend: Optional[StorageBackend] = None,
    tracer: Optional[Tracer] = None,
    sink_factory: Optional[SinkFactory] = None,
) -> RunResult:
    owns_backend = backend is None
    tracer = tracer or NoopTracer()
    # The backend gets the same tracer: its per-request spans nest under
    # the workload's ReadObject spans (OC-bridge analog).
    backend = backend or open_backend(cfg, tracer=tracer)
    try:
        if cfg.workload.fetch_executor == "native":
            return _run_read_native_executor(cfg, backend)
        return ReadWorkload(
            cfg=cfg,
            backend=backend,
            tracer=tracer,
            sink_factory=sink_factory,
        ).run()
    finally:
        if owns_backend:
            backend.close()


def _run_read_native_executor(cfg: BenchConfig, backend: StorageBackend) -> RunResult:
    """The read fan-out on the C++ fetch executor (``tb_pool_*``): the
    reference's errgroup in native code. Worker *i* still owns object
    ``<prefix><i>`` and the in-flight window equals ``--worker``, so each
    logical worker has one outstanding read (the serial per-worker loop's
    concurrency shape) — but dispatch, keep-alive, receive, and timing all
    run on pool pthreads; Python only drains completions.

    Scope (validated loudly): plain-http endpoints, ``staging.mode ==
    "none"`` — the executor measures fetch fan-out; staged ingest uses the
    Python-orchestrated paths. The client-level retry policy does NOT
    apply here (the executor's only recovery is the one stale-connection
    retransmit); ``extra["client_retry"]`` records that.
    """
    from tpubench.native.engine import get_engine
    from tpubench.storage.gcs_http import GcsHttpBackend

    w = cfg.workload
    engine = get_engine()
    if engine is None:
        raise RuntimeError(
            "workload.fetch_executor='native' but the native engine is "
            "unavailable (C++ toolchain missing?)"
        )
    inner = getattr(backend, "inner", backend)
    if not isinstance(inner, GcsHttpBackend) or inner.scheme != "http":
        raise ValueError(
            "fetch_executor='native' requires --protocol http with a "
            "plain-http endpoint (the executor's scope)"
        )
    if cfg.staging.mode != "none":
        raise ValueError(
            "fetch_executor='native' supports staging 'none' only "
            "(it measures fetch fan-out; staged ingest uses the Python "
            "orchestration paths)"
        )

    names = [f"{w.object_name_prefix}{i}" for i in range(w.workers)]
    sizes = {n: inner.stat(n).size for n in set(names)}
    metrics = MetricSet()
    recorders = [metrics.new_worker(f"w{i}") for i in range(w.workers)]
    reads_per = w.read_calls_per_worker
    total_reads = w.workers * reads_per
    if total_reads <= 0:
        # The Python path with zero reads does nothing; match it (and
        # avoid a tag-collision degenerate submit loop).
        res = RunResult(
            workload="read", config=cfg.to_dict(), summaries={},
        )
        res.extra["fetch_executor"] = "native"
        return res
    pool = engine.pool_create(threads=w.workers, cap=max(4, 2 * w.workers))
    inflight: dict[int, tuple] = {}  # tag -> (buffer, worker_id, size)
    free_bufs: dict[int, list] = {}
    bytes_total = 0
    errors = 0
    first_error = ""

    def submit(wid: int, seq: int) -> None:
        name = names[wid]
        size = max(4096, sizes[name])
        bucket = free_bufs.setdefault(size, [])
        buf = bucket.pop() if bucket else engine.alloc(size)
        host, port, path, headers = inner.native_request_parts(name)
        pool.submit(
            host, port, path, buf, headers=headers,
            tag=wid * reads_per + seq,
        )
        inflight[wid * reads_per + seq] = (buf, wid, size)

    from tpubench.obs.exporters import metrics_session_from_config

    session = metrics_session_from_config(
        cfg, metrics, bytes_fn=lambda: bytes_total
    )
    metrics.ingest.start()
    try:
        if session is not None:
            session.__enter__()
        # One outstanding read per logical worker — the serial per-worker
        # loop's concurrency shape; a completion of worker `wid`'s read
        # refills the SAME worker (a fast object never accumulates extra
        # in-flight reads while a slow one starves).
        per_worker_next = [1] * w.workers
        for wid in range(w.workers):
            submit(wid, 0)
        completed = 0
        while completed < total_reads:
            c = pool.next(timeout_ms=120_000)
            if c is None:
                raise RuntimeError("native fetch executor stalled (120s)")
            buf, wid, size = inflight.pop(c["tag"])
            read_rec, fb_rec = recorders[wid]
            failed = c["result"] < 0 or c["status"] not in (200, 206)
            if failed:
                errors += 1
                if not first_error:
                    first_error = (
                        f"worker {wid}: result {c['result']} "
                        f"status {c['status']}"
                    )
            else:
                read_rec.record_ns(c["total_ns"])
                if c["first_byte_ns"]:
                    fb_rec.record_ns(c["first_byte_ns"] - c["start_ns"])
                bytes_total += c["result"]
            free_bufs.setdefault(size, []).append(buf)
            completed += 1
            if failed and w.abort_on_error:
                # errgroup semantics (main.go:200-219): first error
                # cancels the run — same contract as the Python path.
                raise RuntimeError(
                    f"native fetch executor: read failed ({first_error})"
                )
            if per_worker_next[wid] < reads_per:
                submit(wid, per_worker_next[wid])
                per_worker_next[wid] += 1
    finally:
        # Stop the clock BEFORE teardown (thread joins + multi-MB munmaps
        # must not bias the measured window vs the Python path).
        metrics.ingest.stop()
        metrics.ingest.bytes = bytes_total
        if session is not None:
            session.__exit__(None, None, None)  # guaranteed final flush
        pool.close()
        for bucket in free_bufs.values():
            for buf in bucket:
                buf.free()
        for buf, _, _ in inflight.values():
            buf.free()

    wall = metrics.ingest.seconds
    res = RunResult(
        workload="read",
        config=cfg.to_dict(),
        bytes_total=bytes_total,
        wall_seconds=wall,
        gbps=metrics.ingest.gbps(),
        gbps_per_chip=metrics.ingest.gbps(),
        n_chips=1,
        summaries=metrics.summaries(),
        errors=errors,
    )
    res.extra["fetch_executor"] = "native"
    res.extra["executor_threads"] = w.workers
    res.extra["client_retry"] = "not applied (executor scope: one stale-connection retransmit only)"
    if session is not None:
        res.extra["metrics_export"] = session.summary()
    if first_error:
        res.extra["first_error"] = first_error
    return res
