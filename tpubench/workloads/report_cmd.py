"""``tpubench report`` — summarize and compare result JSONs offline.

The reference's post-processing is a matplotlib recipe pasted in its
README (`/root/reference/README.md:15-36`: read per-read latency lines,
print the average, show a histogram). This replaces it with a
dependency-free report over the framework's own result files
(``write_result`` JSONs): the ssd_test percentile block per summary
(Avg/P20/P50/P90/p99/Min/Max — ``ssd_test/main.go:157-163`` format), a
throughput line per run, and — given two or more runs — pairwise deltas
grouped by config axis (transport bit, staging mode, fetch executor),
which is the h1-vs-h2 / h2-vs-grpc / python-vs-native A/B table the
sweep produces.

Pure functions over parsed dicts; the CLI wires file loading around them.
"""

from __future__ import annotations

import json

from tpubench.metrics.percentiles import PCT_FIELDS


def _cell(d, fmt, *path):
    """Dig ``path`` out of nested dict ``d``; format or n/a — the ONE
    cell formatter for every A/B diff line in :func:`compare_runs`."""
    for k in path:
        d = (d or {}).get(k)
    return fmt.format(d) if d is not None else "n/a"


def _transport_bit(t: dict) -> str:
    """The transport axis bit of a run's A/B label: protocol plus the
    wire variant that makes two arms comparable-but-different — h2 or
    native receive for HTTP, DirectPath for gRPC. Transport is a
    first-class A/B axis: an h2 arm and a grpc arm under the same fault
    plan must never render as twins, and :func:`compare_runs` keys its
    transport diff block off this bit differing."""
    proto = t.get("protocol", "?")
    if proto == "grpc":
        if t.get("native_receive"):
            proto += "+native"
        elif t.get("directpath"):
            proto += "+dp"
    elif t.get("http2"):
        proto += "+h2"
    elif t.get("native_receive"):
        proto += "+native"
    return proto


def _axis(run: dict) -> str:
    """The config axis label an A/B varies: transport bit (protocol +
    h2/native/DirectPath), the staging mode, and the fetch executor."""
    cfg = run.get("config", {})
    t = cfg.get("transport", {})
    w = cfg.get("workload", {})
    s = cfg.get("staging", {})
    bits = [_transport_bit(t)]
    if s.get("mode") and s.get("mode") != "none":
        bits.append(f"staging={s['mode']}")
    if w.get("fetch_executor") and w.get("fetch_executor") != "python":
        bits.append(f"executor={w['fetch_executor']}")
    sweep = run.get("extra", {}).get("sweep")
    if sweep:
        bits.append(f"size={sweep.get('size')}")
    if run.get("workload") == "train_ingest":
        ra = (cfg.get("pipeline") or {}).get("readahead", 0)
        bits.append(f"readahead={ra}" if ra else "cold")
        # Coop-vs-per-host is the pod-cache A/B's axis: a cooperative
        # run must not render as a twin of its per-host-cache baseline.
        if (cfg.get("coop") or {}).get("enabled"):
            bits.append("coop")
        # Slab-vs-bytes is the copies A/B's axis: label it so the diff
        # table reads "slab vs bytes", not two identical rows.
        copies = (run.get("extra", {}).get("pipeline") or {}).get("copies")
        if copies and copies.get("mode"):
            bits.append(copies["mode"])
    if run.get("workload") == "serve":
        # QoS-on vs QoS-off is the serve A/B's axis: the protected run
        # must not render as a twin of its baseline arm.
        sv = run.get("extra", {}).get("serve") or {}
        bits.append("serve " + ("qos" if sv.get("qos") else "qos-off"))
        if sv.get("sweep"):
            bits.append("sweep")
    dr = run.get("extra", {}).get("drill")
    if dr:
        # The drill's own A/B axes: restore-through-coop vs direct-to-
        # origin, and delta vs full saves — the two arms the scorecard
        # diff exists to compare must never render as twins.
        arm = dr.get("arm") or {}
        bits.append(
            "drill "
            + ("coop" if arm.get("restore_via_coop") else "direct")
            + ("+delta" if arm.get("delta_saves") else "+full")
        )
        if run.get("extra", {}).get("drill_sweep"):
            bits.append("save-sweep")
    rp = run.get("extra", {}).get("replay")
    if rp:
        # Replay runs label the bundle they re-drove; an A/B replay
        # (different system fingerprint than the original) must not
        # render as a twin of the faithful regression arm.
        bits.append(f"replay:{rp.get('bundle', '?')}")
        if not rp.get("config_match"):
            bits.append("ab")
    mb = run.get("extra", {}).get("membership")
    if mb:
        # Elastic-pod runs carry their own A/B axis: the cooperative-
        # leave arm (handoff bytes flowed) vs the killed-host arm must
        # not render as twins.
        bits.append(f"elastic {mb.get('hosts', 0)}h")
        actions = {e.get("action") for e in mb.get("events", ())}
        if "leave_host" in actions:
            bits.append("coop-leave")
        if "kill_host" in actions:
            bits.append("killed")
    lc = run.get("extra", {}).get("lifecycle")
    if lc:
        # Lifecycle runs label their op + the knob that shapes the A/B
        # (part size for saves, arrival process for storms) so a faulted
        # save doesn't render as a twin of its clean baseline.
        bits.append(f"lifecycle:{lc.get('op', '?')}")
        if lc.get("op") == "save" and lc.get("part_bytes"):
            bits.append(f"part={lc['part_bytes']}")
        if lc.get("op") == "meta_storm":
            bits.append(lc.get("arrival", "?"))
            if lc.get("sweep"):
                bits.append("sweep")
    # Adaptive-vs-static is an A/B axis of its own: a run the controller
    # drove must not render as a twin of its static sibling.
    if (run.get("extra", {}).get("tune") or {}).get("enabled") or \
            run.get("workload") == "tune":
        bits.append("tuned")
    return " ".join(bits)


def percentile_block(name: str, s: dict) -> str:
    """One summary in the ssd_test block format (one line; field order
    shared with the live-run renderer via PCT_FIELDS)."""
    cells = "  ".join(
        f"{h}: {s.get(k, 0.0):.3f} ms" for h, k in PCT_FIELDS
    )
    return f"{name} (n={s.get('count', 0)}): {cells}"


def summarize_run(run: dict, label: str = "") -> str:
    lines = [
        f"== {label or _axis(run)} — {run.get('workload', '?')} ==",
        (
            f"bytes={run.get('bytes_total', 0)} "
            f"wall={run.get('wall_seconds', 0.0):.3f}s "
            f"GB/s={run.get('gbps', 0.0):.4f} "
            f"GB/s/chip={run.get('gbps_per_chip', 0.0):.4f} "
            f"errors={run.get('errors', 0)}"
        ),
    ]
    for name, s in (run.get("summaries") or {}).items():
        lines.append("  " + percentile_block(name, s))
    extra = run.get("extra", {})
    staged = extra.get("staged_gbps_per_chip")
    if staged is not None:
        lines.append(f"  staged GB/s/chip={staged:.4f}")
    staging = extra.get("staging")
    if staging:
        # The overlap story: in-flight window depth, transfers-in-flight
        # gauge, and how much transfer flight time was hidden from the
        # fetch threads (staging_efficiency).
        from tpubench.staging.stats import format_staging_block

        lines.append(format_staging_block(staging))
    if "checksum_ok" in extra:
        lines.append(f"  checksum_ok={extra['checksum_ok']}")
    chaos = extra.get("chaos")
    if chaos:
        # The resilience scorecard travels in the result file; render it
        # with the same body `tpubench chaos` printed live.
        from tpubench.workloads.chaos import format_scorecard

        lines.append(format_scorecard(chaos))
    pipe = extra.get("pipeline")
    if pipe:
        # Ingest-pipeline scorecard (train-ingest): same body the CLI
        # printed live — stall accounting, cache hit ratio, prefetch
        # efficiency.
        from tpubench.workloads.train_ingest import format_pipeline_scorecard

        lines.append(format_pipeline_scorecard(pipe))
    sv = extra.get("serve")
    if sv:
        # Serve scorecard / load-sweep curve: the same body `tpubench
        # serve` printed live — per-class SLO attainment, Jain
        # fairness, shedding, and (sweep runs) the knee.
        from tpubench.workloads.serve import format_serve_scorecard

        lines.append(format_serve_scorecard(sv))
    mb = extra.get("membership")
    if mb:
        # Elastic-membership resize scorecard: events with remap/handoff
        # accounting, SLO during resize windows vs steady state,
        # origin-byte split, time-to-rewarm.
        from tpubench.workloads.serve import format_membership_scorecard

        lines.append(format_membership_scorecard(mb))
    dr = extra.get("drill")
    if dr:
        # Incident-drill scorecard: same body `tpubench drill` printed
        # live — time-to-restore vs time-to-rewarm, gold SLO during the
        # restore window vs steady state, delta-save ledger, origin-byte
        # amplification.
        from tpubench.workloads.drill import format_drill_scorecard

        lines.append(format_drill_scorecard(dr))
    ds = extra.get("drill_sweep")
    if ds:
        # Save-interval sweep curve with the knee identified.
        from tpubench.workloads.drill import format_drill_sweep

        lines.append(format_drill_sweep(ds))
    fl = extra.get("fleet")
    if fl:
        # Virtual-time fleet block: simulated topology, virtual-vs-real
        # wall clock, the fitted service profile — printed after the
        # serve/membership scorecards it was scored by.
        from tpubench.fleet.driver import format_fleet_block

        lines.append(format_fleet_block(fl))
    rp = extra.get("replay")
    if rp:
        # Replay-vs-original scorecard diff: the same body `tpubench
        # replay` printed live — original vs replayed, fingerprints,
        # and the drift deltas the --fail-on grammar gates on.
        from tpubench.replay.bundle import format_replay_block

        lines.append(format_replay_block(rp))
    lc = extra.get("lifecycle")
    if lc:
        # Storage-lifecycle scorecard: same body the CLI printed live
        # (save goodput/parts/resume counts, time-to-restore, storm
        # knee curve).
        from tpubench.lifecycle import format_lifecycle_scorecard

        lines.append(format_lifecycle_scorecard(lc))
    tel = extra.get("telemetry")
    if tel:
        # Live-telemetry stamp: where the run was scrapeable and what
        # the registry's final rollup said — the post-hoc counterpart of
        # `tpubench top` (and the agreement surface the acceptance test
        # pins against `report timeline`).
        gp = tel.get("goodput", {})
        line = (
            f"  telemetry: scrapes={tel.get('scrapes', 0)} "
            f"ticks={tel.get('ticks', 0)} "
            f"live goodput={gp.get('gbps', 0.0):.4f} GB/s"
        )
        if tel.get("port") is not None:
            line += f" (served on :{tel['port']})"
        otlp = tel.get("otlp")
        if otlp:
            line += (
                f"  otlp: {otlp.get('payloads', 0)} payloads -> "
                f"{otlp.get('endpoint', 'dry_run')}"
            )
        lines.append(line)
    tune = extra.get("tune")
    if tune:
        # Tune block: a `tpubench tune` result carries the full
        # sweep/adaptive/recommendation body; a workload run that merely
        # HAD the controller on carries its convergence trace — render
        # both with the body the CLI printed live.
        from tpubench.workloads.tune_cmd import format_tune_block

        if "mode" in tune:
            lines.append(format_tune_block(tune))
        else:
            lines.append(format_tune_block(
                {"mode": "online", "workload": run.get("workload"),
                 "adaptive": tune,
                 "recommended": tune.get("final") or {}}
            ))
    return "\n".join(lines)


def compare_runs(runs: list[dict]) -> str:
    """Pairwise A/B table vs the FIRST run (the baseline): throughput
    ratio and p50/p99 deltas per summary, labeled by config axis."""
    if len(runs) < 2:
        return ""
    base = runs[0]
    base_label = _axis(base)
    lines = [f"A/B vs baseline [{base_label}]:"]
    for other in runs[1:]:
        label = _axis(other)
        bg, og = base.get("gbps", 0.0), other.get("gbps", 0.0)
        ratio = og / bg if bg > 0 else 0.0
        lines.append(
            f"  [{label}] GB/s {og:.4f} vs {bg:.4f} "
            f"({ratio:.3f}x baseline)"
        )
        for name, s in (other.get("summaries") or {}).items():
            b = (base.get("summaries") or {}).get(name)
            if not b:
                continue
            d50 = s.get("p50_ms", 0.0) - b.get("p50_ms", 0.0)
            d99 = s.get("p99_ms", 0.0) - b.get("p99_ms", 0.0)
            lines.append(
                f"    {name}: p50 {s.get('p50_ms', 0.0):.3f} ms "
                f"({d50:+.3f}), p99 {s.get('p99_ms', 0.0):.3f} ms "
                f"({d99:+.3f})"
            )
        cell = _cell
        # Transport diff: the first-class A/B axis the gRPC plane adds.
        # An h2 arm against a grpc arm under the same fault plan compares
        # on what the transport exists for — goodput, read tail, watchdog
        # stalls, and (when the arms wrote) checkpoint save goodput
        # through the same wire faults.
        ot_ = (other.get("config") or {}).get("transport") or {}
        bt_ = (base.get("config") or {}).get("transport") or {}
        o_bit, b_bit = _transport_bit(ot_), _transport_bit(bt_)
        if o_bit != b_bit:
            def _read_p99(doc):
                ss = doc.get("summaries") or {}
                s_ = ss.get("read") or next(iter(ss.values()), None)
                return s_.get("p99_ms") if s_ else None

            def _stalls(doc):
                return ((doc.get("extra", {}).get("tail") or {})
                        .get("watchdog") or {}).get("stalls", 0)

            def _save_gbps(doc):
                lc_ = doc.get("extra", {}).get("lifecycle") or {}
                return (lc_.get("goodput_gbps")
                        if lc_.get("op") == "save" else None)

            def _na(v, fmt):
                return fmt.format(v) if v is not None else "n/a"

            tline = (
                f"    transport [{o_bit} vs {b_bit}]: goodput "
                f"{og:.4f} vs {bg:.4f} GB/s, read p99 "
                f"{_na(_read_p99(other), '{:.3f}ms')} vs "
                f"{_na(_read_p99(base), '{:.3f}ms')}, "
                f"stalls {_stalls(other)} vs {_stalls(base)}"
            )
            osg, bsg = _save_gbps(other), _save_gbps(base)
            if osg is not None or bsg is not None:
                tline += (
                    ", save goodput "
                    f"{_na(osg, '{:.4f}')} vs {_na(bsg, '{:.4f}')} GB/s"
                )
            lines.append(tline)
        # Pipeline diff: two train-ingest runs (readahead on vs cold)
        # compare on what the pipeline exists for — stall time, stalled
        # fraction, hit ratio — not just throughput.
        op_ = other.get("extra", {}).get("pipeline")
        bp = base.get("extra", {}).get("pipeline")
        if op_ and bp:
            lines.append(
                "    pipeline: stalled "
                f"{cell(op_, '{:.1%}', 'stall', 'stalled_fraction')} vs "
                f"{cell(bp, '{:.1%}', 'stall', 'stalled_fraction')}, "
                "stall p99 "
                f"{cell(op_, '{:.2f}ms', 'stall', 'p99_ms')} vs "
                f"{cell(bp, '{:.2f}ms', 'stall', 'p99_ms')}, "
                "hit ratio "
                f"{cell(op_, '{:.1%}', 'cache', 'hit_ratio')} vs "
                f"{cell(bp, '{:.1%}', 'cache', 'hit_ratio')}"
            )
            if op_.get("coop") or bp.get("coop"):
                # Coop-vs-per-host diff: the axis that matters is origin
                # bytes fetched (per pod) — the per-host baseline pays
                # them N times; peer hit ratio says where they went
                # instead.
                lines.append(
                    "    coop: origin_bytes "
                    f"{cell(op_, '{}', 'coop', 'origin_bytes')} vs "
                    f"{cell(bp, '{}', 'coop', 'origin_bytes')}, "
                    "peer hit "
                    f"{cell(op_, '{:.1%}', 'coop', 'peer_hit_ratio')} vs "
                    f"{cell(bp, '{:.1%}', 'coop', 'peer_hit_ratio')}, "
                    "pod_coalesced "
                    f"{cell(op_, '{}', 'coop', 'pod_coalesced')} vs "
                    f"{cell(bp, '{}', 'coop', 'pod_coalesced')}"
                )
            if op_.get("copies") or bp.get("copies"):
                # The zero-copy A/B's headline: host-RAM writes per
                # delivered chunk byte (slab = 1.00, legacy bytes >= 2).
                lines.append(
                    "    copies/byte "
                    f"{cell(op_, '{:.2f}', 'copies', 'copies_per_byte')} "
                    f"({cell(op_, '{}', 'copies', 'mode')}) vs "
                    f"{cell(bp, '{:.2f}', 'copies', 'copies_per_byte')} "
                    f"({cell(bp, '{}', 'copies', 'mode')})"
                )
        osv = other.get("extra", {}).get("serve")
        bsv = base.get("extra", {}).get("serve")
        if osv and bsv and not (osv.get("sweep") or bsv.get("sweep")):
            # The QoS A/B's verdict line: did the protected class keep
            # its SLO, what did the protection cost in aggregate
            # goodput, and how fair was each arm (Jain over weight-
            # normalized per-tenant goodput).
            def _gold(sv):
                cl = sv.get("classes") or {}
                return min(
                    cl.values(), key=lambda x: x.get("priority", 0)
                ) if cl else {}

            og, bg_ = _gold(osv), _gold(bsv)
            bgp = bsv.get("goodput_gbps") or 0.0
            ogp = osv.get("goodput_gbps") or 0.0
            retention = (ogp / bgp) if bgp > 0 else None
            lines.append(
                "    serve: gold SLO "
                f"{cell(og, '{:.1%}', 'slo_attainment')} vs "
                f"{cell(bg_, '{:.1%}', 'slo_attainment')}, "
                "gold p99 "
                f"{cell(og, '{:.1f}ms', 'p99_ms')} vs "
                f"{cell(bg_, '{:.1f}ms', 'p99_ms')}, "
                "shed "
                f"{osv.get('shed', 0)} vs {bsv.get('shed', 0)}, "
                "jain "
                f"{cell(osv, '{:.3f}', 'jain_fairness')} vs "
                f"{cell(bsv, '{:.3f}', 'jain_fairness')}"
                + (
                    f", goodput retention {retention:.1%}"
                    if retention is not None else ""
                )
            )
        # Membership diff: the cooperative-leave arm against the
        # killed-host arm compares on what elastic membership exists
        # for — did the warm handoff replace origin re-fetches during
        # the resize window, and did the protected class's SLO survive
        # the reshape.
        omb = other.get("extra", {}).get("membership")
        bmb = base.get("extra", {}).get("membership")
        if omb and bmb:
            def _gold_resize(mb):
                # "gold" = the first entry: the scorecard writes classes
                # in priority order, so insertion order IS rank.
                slo = (mb.get("slo") or {}).get("resize") or {}
                for v in slo.values():
                    return v
                return None

            og_, bg2 = _gold_resize(omb), _gold_resize(bmb)
            lines.append(
                "    membership: handoff "
                f"{(omb.get('handoff') or {}).get('out_bytes', 0)}B vs "
                f"{(bmb.get('handoff') or {}).get('out_bytes', 0)}B, "
                "resize-window origin "
                f"{(omb.get('origin_bytes') or {}).get('resize_windows', 0)}B vs "
                f"{(bmb.get('origin_bytes') or {}).get('resize_windows', 0)}B, "
                "gold SLO during resize "
                + (f"{og_:.1%}" if og_ is not None else "n/a")
                + " vs "
                + (f"{bg2:.1%}" if bg2 is not None else "n/a")
                + ", failovers "
                f"{omb.get('failovers', 0)} vs {bmb.get('failovers', 0)}"
            )
        # Drill diff: the restore-through-coop arm against the direct-
        # to-origin arm (or delta vs full saves) compares on what the
        # drill exists for — time-to-restore, the protected class's SLO
        # through the restore window, origin-byte amplification, and
        # what the save cadence uploaded.
        odr = other.get("extra", {}).get("drill")
        bdr = base.get("extra", {}).get("drill")
        if odr and bdr:
            def _gold_restore_slo(doc, dr):
                # Gold = the min-priority serving class; the restore
                # class never appears in the arrival-SLO tally.
                cl = (doc.get("extra", {}).get("serve") or {}) \
                    .get("classes") or {}
                win = (dr.get("gold_slo") or {}).get("restore_window") or {}
                names = [n for n in win if n in cl]
                if not names:
                    return None
                gold = min(names, key=lambda n: cl[n].get("priority", 0))
                return win.get(gold)

            og2 = _gold_restore_slo(other, odr)
            bg3 = _gold_restore_slo(base, bdr)
            lines.append(
                "    drill: time-to-restore "
                f"{cell(odr, '{:.3f}s', 'restore', 'time_to_restore_s')} vs "
                f"{cell(bdr, '{:.3f}s', 'restore', 'time_to_restore_s')}, "
                "gold SLO in restore window "
                + (f"{og2:.1%}" if og2 is not None else "n/a")
                + " vs "
                + (f"{bg3:.1%}" if bg3 is not None else "n/a")
                + ", amplification "
                f"{cell(odr, '{:.2f}x', 'amplification', 'ratio')} vs "
                f"{cell(bdr, '{:.2f}x', 'amplification', 'ratio')}, "
                "save bytes "
                f"{(odr.get('saves') or {}).get('bytes_uploaded', 0)} vs "
                f"{(bdr.get('saves') or {}).get('bytes_uploaded', 0)}, "
                "cas conflicts "
                f"{(odr.get('saves') or {}).get('cas_conflicts', 0)} vs "
                f"{(bdr.get('saves') or {}).get('cas_conflicts', 0)}"
            )
        # Lifecycle diff: two saves (e.g. faulted vs clean, or part-size
        # A/B) compare on what the write path exists for — goodput,
        # resumed parts, part-level tail; restores on time-to-restore;
        # storms on the knee.
        olc = other.get("extra", {}).get("lifecycle")
        blc = base.get("extra", {}).get("lifecycle")
        if olc and blc and olc.get("op") == blc.get("op"):
            op = olc.get("op")
            if op == "save":
                lines.append(
                    "    ckpt-save: goodput "
                    f"{cell(olc, '{:.4f}', 'goodput_gbps')} vs "
                    f"{cell(blc, '{:.4f}', 'goodput_gbps')} GB/s, "
                    "part p99 "
                    f"{cell(olc, '{:.2f}ms', 'part_latency', 'p99_ms')} vs "
                    f"{cell(blc, '{:.2f}ms', 'part_latency', 'p99_ms')}, "
                    "resumed "
                    f"{olc.get('resumed_parts', 0)} vs "
                    f"{blc.get('resumed_parts', 0)}, corrupt "
                    f"{olc.get('corrupt_finalizes', 0)} vs "
                    f"{blc.get('corrupt_finalizes', 0)}"
                )
            elif op == "restore":
                lines.append(
                    "    ckpt-restore: time-to-restore "
                    f"{cell(olc, '{:.3f}s', 'time_to_restore_s')} vs "
                    f"{cell(blc, '{:.3f}s', 'time_to_restore_s')}, "
                    "goodput "
                    f"{cell(olc, '{:.4f}', 'goodput_gbps')} vs "
                    f"{cell(blc, '{:.4f}', 'goodput_gbps')} GB/s"
                )
            elif op == "meta_storm":
                lines.append(
                    "    meta-storm: achieved "
                    f"{cell(olc, '{:.1f}', 'achieved_rps')} vs "
                    f"{cell(blc, '{:.1f}', 'achieved_rps')} rps, "
                    "p99 "
                    f"{cell(olc, '{:.2f}ms', 'p99_ms')} vs "
                    f"{cell(blc, '{:.2f}ms', 'p99_ms')}, "
                    "knee "
                    f"{cell(olc, '{}', 'sweep', 'knee', 'offered_rps')} vs "
                    f"{cell(blc, '{}', 'sweep', 'knee', 'offered_rps')}"
                )
        # Tune diff: a static run against its adaptive sibling compares
        # on what the controller exists for — the converged operating
        # point and when it got there — alongside the throughput ratio
        # already printed above.
        for side, label in ((other, "B"), (base, "A")):
            tn = (side.get("extra", {}).get("tune") or {})
            ad = tn.get("adaptive") if "mode" in tn else tn
            if ad and ad.get("enabled"):
                conv = ad.get("windows_to_converge")
                lines.append(
                    f"    tune[{label}]: {ad.get('initial')} -> "
                    f"{ad.get('final')}"
                    + (f", converged in {conv} windows"
                       if ad.get("converged") else ", not converged")
                )
        # Replay diff: two replays of the same bundle under different
        # system configs compare on what replay exists for — how far
        # each drifted from the recorded original.
        orp = other.get("extra", {}).get("replay")
        brp = base.get("extra", {}).get("replay")
        if orp and brp:
            od, bd = orp.get("diff") or {}, brp.get("diff") or {}
            lines.append(
                f"    replay[{orp.get('bundle', '?')}]: retention "
                f"{cell(od, '{:.1%}', 'goodput_retention')} vs "
                f"{cell(bd, '{:.1%}', 'goodput_retention')}, "
                "gold SLO delta "
                f"{cell(od, '{:+.1f}pts', 'gold_slo_delta_pts')} vs "
                f"{cell(bd, '{:+.1f}pts', 'gold_slo_delta_pts')}, "
                "p99 "
                f"{cell(od, '{:.2f}x', 'p99_ratio')} vs "
                f"{cell(bd, '{:.2f}x', 'p99_ratio')}"
            )
        # Scorecard diff: two chaos runs (e.g. hedged vs unhedged over the
        # same timeline) compare on resilience, not just throughput.
        osc = (other.get("extra", {}).get("chaos") or {}).get("scorecard")
        bsc = (base.get("extra", {}).get("chaos") or {}).get("scorecard")
        if osc and bsc:
            lines.append(
                "    scorecard: retention "
                f"{cell(osc, '{:.1%}', 'goodput_retention')} vs "
                f"{cell(bsc, '{:.1%}', 'goodput_retention')}, "
                "p99 inflation "
                f"{cell(osc, '{:.2f}x', 'p99_inflation')} vs "
                f"{cell(bsc, '{:.2f}x', 'p99_inflation')}, "
                "time-to-recover "
                f"{cell(osc, '{:.3f}s', 'time_to_recover_s')} vs "
                f"{cell(bsc, '{:.3f}s', 'time_to_recover_s')}"
            )
    return "\n".join(lines)


def sweep_table(rows: list[dict]) -> str:
    """Table form of a ``tpubench sweep`` output (the list of cells the
    sweep command prints/writes)."""
    if not rows:
        return ""
    lines = ["sweep:"]
    for r in rows:
        cell = f"  {r.get('protocol', '?'):>8}"
        if "native_receive" in r:
            cell += f"/{'native' if r['native_receive'] else 'python'}"
        cell += (
            f"  size={r.get('size', '?'):>6}  GB/s={r.get('gbps', 0.0):.4f}"
            f"  p50={r.get('p50_ms', 0.0):.3f} ms"
            f"  p99={r.get('p99_ms', 0.0):.3f} ms"
        )
        lines.append(cell)
    return "\n".join(lines)


def bench_block(d: dict, label: str = "") -> str:
    """Summary of a bench.py output line (or a driver BENCH_rN.json's
    ``parsed`` object): the headline with its honest comparables and the
    per-config sample sets."""
    lines = [
        f"== bench {label} ==".rstrip(),
        (
            f"{d.get('metric', '?')}: {d.get('value', 0.0)} "
            f"{d.get('unit', '')}  config={d.get('config', '?')}  "
            f"shaped={d.get('shaped_verdict')}"
        ),
        (
            f"  vs_baseline={d.get('vs_baseline')}  "
            f"vs_tunnel_ceiling={d.get('vs_tunnel_ceiling')}  "
            f"staging_efficiency={d.get('staging_efficiency')}"
        ),
    ]
    ebm = d.get("efficiency_by_mode")
    if ebm:
        cells = "  ".join(
            f"{mode}: best={v.get('best')}"
            + (f" median={v['median']}" if v.get("median") is not None else "")
            for mode, v in ebm.items()
        )
        lines.append(f"  efficiency_by_mode: {cells}")
    ab = d.get("fetch_only_ab") or {}
    if ab.get("native_executor_gbps") and ab.get("python_fetch_gbps"):
        lines.append(
            f"  fetch A/B: native {ab['native_executor_gbps']} vs "
            f"python {ab['python_fetch_gbps']} GB/s ({ab.get('source', '')})"
        )
    for cfg, samples in (d.get("samples") or {}).items():
        lines.append(f"  {cfg}: {samples}")
    return "\n".join(lines)


def multichip_block(d: dict, label: str = "") -> str:
    """Summary of a MULTICHIP_SWEEP.json artifact: per-size pod-ingest
    stage split and the per-collective best rows, with the ring-algebra
    verdict."""
    lines = [f"== multichip sweep {label} ==".rstrip()]
    lines.append(
        f"sizes={d.get('sizes')}  shard_mb={d.get('shard_mb')}  "
        f"ring_algebra_ok={d.get('ring_algebra_ok')}"
    )
    for entry in d.get("pod_ingest") or []:
        for key, tag in (
            ("pod_ingest_all_gather", "all_gather"),
            ("pod_ingest_ring", "ring"),
        ):
            pi = entry.get(key) or {}
            if not pi:
                continue
            lines.append(
                f"  n={entry.get('devices', '?'):>2} {tag:>10}:"
                f" fetch {pi.get('fetch_seconds', 0):.3f}s"
                f"  stage {pi.get('stage_seconds', 0):.3f}s"
                f"  gather {pi.get('gather_seconds', 0):.3f}s"
                f"  ingest {pi.get('ingest_gbps', 0):.3f} GB/s"
                f"  verified={pi.get('verified')}"
            )
    for mode, rows in (d.get("collectives") or {}).items():
        if rows:
            best = max(rows, key=lambda r: r.get("per_chip_rx_gbps", 0))
            lines.append(
                f"  {mode}: best n={best.get('devices', '?')} "
                f"{best.get('per_chip_rx_gbps', 0):.3f} GB/s/chip rx"
            )
    return "\n".join(lines)


def run_timeline(paths: list[str]) -> str:
    """``tpubench report timeline <journal...>`` — merge per-host flight
    journals (obs/flight.py) into the pod-level per-phase p50/p99 report
    with straggler attribution. One file = single-host timeline; many =
    the cross-host aggregation pass.

    Sibling discovery rides the live aggregator's glob discipline
    (``obs/live.discover_journal_paths``), so handing the BASE path of a
    serve sweep (or a multi-host run) collects its ``.pt<i>`` /
    ``.p<idx>`` siblings automatically. Sweep points are DIFFERENT runs
    at different offered loads: they render as labeled segments (base
    run, then each point in order), never silently pooled into one
    timeline whose percentiles would belong to no run at all; per-host
    siblings of one point still merge, the cross-host pass."""
    import re

    from tpubench.obs.flight import load_journals, render_timeline
    from tpubench.obs.live import discover_journal_paths

    expanded: list[str] = []
    seen = set()
    for base in paths:
        # Per-base expansion, keeping a missing base so load_journals
        # still emits its one-line unreadable warning for it.
        for p in discover_journal_paths([base]) or [base]:
            if p not in seen:
                seen.add(p)
                expanded.append(p)
    groups: dict = {}
    for p in expanded:
        m = re.search(r"\.pt(\d+)", p)
        groups.setdefault(int(m.group(1)) if m else None, []).append(p)
    if len(groups) <= 1:
        return render_timeline(load_journals(expanded))
    out = [f"== serve sweep timeline: {len(groups)} segments =="]
    for point in sorted(groups, key=lambda k: (k is not None, k or 0)):
        docs = load_journals(groups[point])
        if not docs:
            continue
        label = "base run" if point is None else f"sweep point {point}"
        out.append(
            f"-- {label} ({', '.join(groups[point])}) --\n"
            + render_timeline(docs)
        )
    return "\n\n".join(out)


def run_trace(paths: list[str], *, slow_fraction: float = 0.1,
              head_rate: float = 0.05, max_keep: int = 512,
              show: int = 3) -> str:
    """``tpubench report trace <journal...>`` — merge per-host flight
    journals into cross-host span trees (the records' trace_id/span_id/
    parent_id graph), tail-sample per trace (slowest decile + unbiased
    head sample), and print the p99 blame table + the slowest trees
    with per-span critical-path durations."""
    from tpubench.obs.flight import load_journals
    from tpubench.obs.trace import render_trace_report

    return render_trace_report(
        load_journals(paths), slow_fraction=slow_fraction,
        head_rate=head_rate, max_keep=max_keep, show=show,
    )


def run_report(paths: list[str]) -> str:
    """Load result/sweep/bench JSONs and render the full report."""
    runs: list[dict] = []
    chunks: list[str] = []
    for p in paths:
        with open(p) as f:
            doc = json.load(f)
        if isinstance(doc, list):  # a sweep cells file
            chunks.append(sweep_table(doc))
            continue
        if doc.get("format") == "tpubench-flight-v1":
            # A flight journal handed to the plain report renders as a
            # single-host timeline (same body as `report timeline`).
            from tpubench.obs.flight import render_timeline

            chunks.append(render_timeline([doc]))
            continue
        if "metric" in doc:  # a bench.py output line saved to a file
            chunks.append(bench_block(doc, label=f"({p})"))
            continue
        if "ring_algebra_ok" in doc:  # a MULTICHIP_SWEEP.json artifact
            chunks.append(multichip_block(doc, label=f"({p})"))
            continue
        if "rc" in doc and "tail" in doc:
            # Driver BENCH_rN.json wrapper: summarize the parsed bench
            # line when there is one; a failed run (no usable `parsed`)
            # is reported as such — never fed to the A/B comparison as a
            # bogus zero-throughput baseline.
            if isinstance(doc.get("parsed"), dict) and "metric" in doc["parsed"]:
                chunks.append(bench_block(doc["parsed"], label=f"({p})"))
            else:
                chunks.append(
                    f"== bench ({p}) ==\n"
                    f"  run failed or unparsed (rc={doc.get('rc')}); "
                    "see its `tail` for the crash output"
                )
            continue
        runs.append(doc)
        chunks.append(summarize_run(doc, label=f"{_axis(doc)} ({p})"))
    cmp_block = compare_runs(runs)
    if cmp_block:
        chunks.append(cmp_block)
    return "\n\n".join(c for c in chunks if c)
