"""``tpubench serve`` — open-loop multi-tenant traffic plane.

Every other tpubench workload is closed-loop: a fixed worker pool pulls
as fast as it can, so the measured operating point is always "saturated
by construction" and there is no knee to find. Production ingest is the
opposite regime — requests arrive from many tenants on their own
schedule whether or not the system keeps up — and the questions that
matter are open-loop questions: where is the saturation knee, what does
p99 do as offered load approaches it, who gets hurt past it, and does
QoS actually protect the tenants that paid for protection.

Mechanics (the full stack, nothing stubbed):

* a pre-generated **arrival schedule** (``workloads/arrivals``: Poisson,
  bursty MMPP, diurnal, replayed trace — seeded, replayable) assigns
  each arrival to one of thousands of synthetic tenants in weighted
  priority classes, each tenant drawing chunks from a shared Zipf hot
  set;
* a **dispatcher** replays the schedule in real time (gaps scaled by the
  shared ``TPUBENCH_BENCH_SLEEP_SCALE`` contract, floored so bursts stay
  bursts) into the :class:`~tpubench.serve.qos.AdmissionQueue` —
  priority admission with a LIVE cap (the PR-5 runnable-queue admission
  hook, tune-actuatable) and deadline-aware shedding under overload;
* **service workers** resolve each request through the chunk cache
  (weighted per-class budgets + single-flight) and the full
  ``open_backend`` stack (hedge/watchdog/breaker/retry compose under
  serve exactly as under every other workload), with optional readahead
  over the schedule (per-class prefetch byte budgets);
* the **scorecard** (``extra["serve"]``) reports per-class SLO
  attainment, p50/p99, shed counts by reason, Jain fairness over
  weight-normalized per-tenant goodput, and goodput-under-overload;
  ``run_serve_sweep`` steps offered load and locates the knee
  (``serve.qos.find_knee``).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

import numpy as np

from tpubench.config import (
    BenchConfig,
    parse_sleep_scale,
    validate_serve_config,
)
from tpubench.metrics.percentiles import summarize_ns
from tpubench.metrics.recorder import LatencyRecorder
from tpubench.metrics.report import RunResult
from tpubench.obs.flight import (
    flight_from_config,
    host_journal_path,
    transport_label,
)
from tpubench.obs.telemetry import telemetry_from_config
from tpubench.pipeline.cache import ChunkCache
from tpubench.pipeline.prefetch import Prefetcher, fetch_chunk
from tpubench.serve.qos import (
    AdmissionQueue,
    ClassLedger,
    Request,
    build_tenants,
    class_budget_split,
    find_knee,
    jain_index,
)
from tpubench.storage import open_backend
from tpubench.storage.base import StorageBackend
from tpubench.workloads.arrivals import (
    load_trace,
    make_arrivals,
    scaled_gaps,
    zipf_keys_weights,
)


def build_schedule(cfg: BenchConfig, backend: StorageBackend,
                   rate_rps: Optional[float] = None,
                   objects: Optional[list] = None) -> list[Request]:
    """The run's merged open-loop schedule: arrival timestamps from the
    configured process, each assigned to a tenant (class-share-weighted)
    and to one chunk of that tenant's Zipf stream. Deterministic for a
    given seed — the replayed-trace property every arrival kind gets.
    ``objects`` lets the caller pass an already-fetched listing (the
    replay stamp must describe the SAME population the schedule was
    built over, never a re-listing that could race a mutating store)."""
    sc = cfg.serve
    w = cfg.workload
    chunk = sc.chunk_bytes or w.granule_bytes
    if objects is None:
        objects = backend.list(w.object_name_prefix)
    if not objects:
        raise SystemExit(
            f"serve: no objects under prefix {w.object_name_prefix!r} "
            "(run `tpubench prepare` or use --protocol fake)"
        )
    tenants = build_tenants(sc.classes, sc.tenants, seed=sc.seed)
    times = make_arrivals(
        sc.arrival, rate_rps if rate_rps is not None else sc.rate_rps,
        sc.duration_s, seed=sc.seed,
        burst_factor=sc.burst_factor, burst_fraction=sc.burst_fraction,
        burst_cycle_s=sc.burst_cycle_s,
        diurnal_period_s=sc.diurnal_period_s,
        trace=load_trace(sc.trace_path) if sc.arrival == "trace" else None,
    )
    # Tenant assignment: class share split evenly over the class's
    # tenants (traffic share and population share use the same knob).
    by_cls: dict[str, list[int]] = {}
    for i, t in enumerate(tenants):
        by_cls.setdefault(t.cls, []).append(i)
    probs = np.zeros(len(tenants), dtype=np.float64)
    share_total = sum(float(c["share"]) for c in sc.classes)
    for c in sc.classes:
        members = by_cls.get(str(c["name"]), [])
        if not members:
            continue
        per = (float(c["share"]) / share_total) / len(members)
        for i in members:
            probs[i] = per
    probs /= probs.sum()
    rng = np.random.Generator(np.random.Philox(sc.seed + 1))
    assign = rng.choice(len(tenants), size=len(times), p=probs)
    # Per-tenant Zipf chunk streams over the SHARED object set: keys
    # and the weight vector are enumerated ONCE (zipf_keys_weights) and
    # only the per-tenant rng draws differ — per-tenant zipf_plan calls
    # would redo O(chunks) setup per tenant for identical data.
    keys, weights = zipf_keys_weights(
        objects, chunk, bucket=w.bucket, alpha=sc.alpha
    )
    counts = np.bincount(assign, minlength=len(tenants))
    streams = {}
    for i, n in enumerate(counts):
        if n:
            trng = np.random.Generator(np.random.Philox(tenants[i].seed))
            streams[i] = iter(
                trng.choice(len(keys), size=int(n), p=weights)
            )
    return [
        Request(
            tenant=tenants[ti], key=keys[next(streams[ti])],
            arrival_s=float(t), index=idx,
        )
        for idx, (t, ti) in enumerate(zip(times, assign))
    ]


class _ShedLog:
    """Serialized flight-note emitter for sheds: shed callbacks fire on
    whichever thread shed (dispatcher push, worker pop, drain), and a
    WorkerFlight ring is single-appender by contract — one small lock
    keeps the breadcrumb path honest."""

    def __init__(self, flight, tlabel: str):
        self._ring = flight.worker("shed") if flight is not None else None
        self._tlabel = tlabel
        self._lock = threading.Lock()

    def __call__(self, req: Request, reason: str) -> None:
        if self._ring is None:
            return
        try:
            with self._lock:
                op = self._ring.begin(
                    req.key.object, self._tlabel, install=False,
                )
                op.note(
                    "shed", cls=req.tenant.cls, reason=reason,
                )
                op.note(
                    "serve_req", cls=req.tenant.cls, outcome="shed",
                )
                op.finish(0)
        except Exception:  # noqa: BLE001 — breadcrumbs must not shed twice
            pass


def run_serve(cfg: BenchConfig, backend: Optional[StorageBackend] = None,
              rate_rps: Optional[float] = None, tracer=None,
              replay_source: Optional[dict] = None) -> RunResult:
    """One open-loop serve run at the configured offered load (or
    ``rate_rps``, the sweep's per-point override). ``serve.hosts > 1``
    fans the same schedule across an N-host elastic pod
    (:class:`_ElasticServe`) whose membership may change mid-run.
    ``replay_source`` (set by ``tpubench replay``) is the identity of
    the bundle this run re-drives; it passes through into the journal's
    replay stamp so re-recording a replay reproduces the ORIGINAL
    bundle."""
    validate_serve_config(cfg.serve)
    owns_backend = backend is None
    backend = backend or open_backend(cfg, tracer=tracer)
    try:
        if cfg.serve.hosts > 1:
            return _ElasticServe(cfg, backend, rate_rps,
                                 replay_source=replay_source).run()
        return _Serve(cfg, backend, rate_rps,
                      replay_source=replay_source).run()
    finally:
        if owns_backend:
            backend.close()


class _Serve:
    def __init__(self, cfg: BenchConfig, backend: StorageBackend,
                 rate_rps: Optional[float],
                 replay_source: Optional[dict] = None):
        self.cfg = cfg
        self.backend = backend
        self.rate_rps = rate_rps
        self.replay_source = replay_source

    def run(self) -> RunResult:
        cfg, sc = self.cfg, self.cfg.serve
        chunk = sc.chunk_bytes or cfg.workload.granule_bytes
        objects = self.backend.list(cfg.workload.object_name_prefix)
        schedule = build_schedule(cfg, self.backend, self.rate_rps,
                                  objects=objects)
        tlabel = transport_label(cfg)
        scale = parse_sleep_scale("serve arrival gaps")
        gaps = scaled_gaps([r.arrival_s for r in schedule], scale)

        qos = sc.qos
        budgets = class_budget_split(sc.classes, cfg.pipeline.cache_bytes) \
            if qos else None
        cache = ChunkCache(cfg.pipeline.cache_bytes, owner_budgets=budgets)
        flight = flight_from_config(cfg)
        shed_log = _ShedLog(flight, tlabel)
        queue = AdmissionQueue(
            cap=sc.admission_cap or sc.workers, qos=qos,
            queue_limit=(sc.queue_limit or 8 * sc.workers) if qos else 0,
            on_shed=shed_log,
        )
        worker_flights = [
            flight.worker(f"serve-{i}") if flight is not None else None
            for i in range(sc.workers)
        ]

        # Per-class ledgers + latency recorders; classes sorted by
        # priority so "the high-priority class" is ledger order 0.
        classes = sorted(
            sc.classes, key=lambda c: int(c.get("priority", 0))
        )
        ledgers = {str(c["name"]): ClassLedger() for c in classes}
        recorders = {
            str(c["name"]): LatencyRecorder(f"request_{c['name']}")
            for c in classes
        }
        agg_rec = LatencyRecorder("request")
        ledger_lock = threading.Lock()
        tenant_bytes: dict[str, int] = {}
        completed_bytes = [0]

        for req in schedule:
            ledgers[req.tenant.cls].arrivals += 1

        # Readahead over the schedule (serve IS a replayed trace — the
        # plan is known ahead, train-ingest style), with per-class byte
        # budgets so one class can't monopolize the window.
        pf: Optional[Prefetcher] = None
        if sc.readahead > 0:
            plan = [r.key for r in schedule]
            owners = [r.tenant.cls for r in schedule] if qos else None
            pf_budgets = class_budget_split(
                sc.classes, sc.readahead * chunk
            ) if qos else None
            pf = Prefetcher(
                self.backend, cache, plan,
                workers=cfg.pipeline.prefetch_workers,
                depth=sc.readahead,
                byte_budget=cfg.pipeline.readahead_bytes,
                transport=tlabel,
                owners=owners, owner_budgets=pf_budgets,
            )
            pf.advance(0)

        # Live telemetry (read.py wiring): flight tap + journal stream.
        jpath_stream = None
        if cfg.obs.flight_journal:
            jpath_stream = host_journal_path(
                cfg.obs.flight_journal, cfg.dist.process_id,
                cfg.dist.num_processes,
            )
        tel = telemetry_from_config(cfg)
        tel_summary = None
        if tel is not None:
            tel.resource["workload"] = "serve"
            if flight is not None:
                tel.attach_flight(flight)
                if jpath_stream:
                    tel.stream_journal(
                        flight, jpath_stream,
                        extra_fn=lambda: {"workload": "serve"},
                        max_bytes=cfg.obs.journal_max_bytes,
                    )
            tel.attach_recorders([agg_rec])
            tel.start()

        def worker(i: int) -> None:
            wf = worker_flights[i]
            while True:
                req = queue.pop()
                if req is None:
                    return
                cls = req.tenant.cls
                t_pop = time.perf_counter_ns()
                op = None
                try:
                    data = cache.get(req.key)
                    if data is not None:
                        source = "hit"
                        if wf is not None:
                            op = wf.begin(
                                req.key.object, tlabel, kind="cache",
                                enqueue_ns=req.enqueue_ns,
                            )
                            op.mark("cache_hit")
                    else:
                        if wf is not None:
                            op = wf.begin(
                                req.key.object, tlabel,
                                enqueue_ns=req.enqueue_ns,
                            )
                            op.mark("cache_miss", t_pop)
                        data, source = cache.get_or_fetch_info(
                            req.key,
                            lambda k=req.key: fetch_chunk(self.backend, k),
                            owner=cls if qos else None,
                        )
                        if op is not None:
                            if source == "hit":
                                # Raced hit: a prefetch (or concurrent
                                # worker) landed the chunk between the
                                # get() probe and this call — the
                                # would-be miss record becomes a cache
                                # record (train-ingest discipline), so
                                # the FETCHER's read record stays the
                                # only byte-carrying one.
                                op.abandon()
                                op = wf.begin(
                                    req.key.object, tlabel, kind="cache",
                                    enqueue_ns=req.enqueue_ns,
                                )
                                op.mark("cache_hit")
                            else:
                                op.mark("body_complete")
                    done_ns = time.perf_counter_ns()
                    met = done_ns <= req.deadline_ns
                    nbytes = len(data)
                    if op is not None:
                        # Storage-byte credit follows the owner-only
                        # discipline (goodput_summary sums kind="read"
                        # bytes; one backend read must count once):
                        # coalesced waits finish with 0, raced hits are
                        # cache records, plain hits took the cache
                        # branch above.
                        op.note(
                            "serve_req", cls=cls, outcome="completed",
                            deadline_met=met,
                        )
                        op.finish(
                            nbytes if source in ("hit", "fetched") else 0
                        )
                    lat_ns = done_ns - req.enqueue_ns
                    with ledger_lock:
                        led = ledgers[cls]
                        led.completed += 1
                        led.bytes += nbytes
                        if met:
                            led.deadline_met += 1
                        tenant_bytes[req.tenant.name] = (
                            tenant_bytes.get(req.tenant.name, 0) + nbytes
                        )
                        completed_bytes[0] += nbytes
                    recorders[cls].record_ns(lat_ns)
                    agg_rec.record_ns(lat_ns)
                except Exception as e:  # noqa: BLE001 — per-request domain
                    # Open-loop serving has per-request failure domains:
                    # one tenant's failed fetch (post-retry) is an error
                    # in its ledger, never a run abort. Exception, NOT
                    # BaseException (the coop serve() discipline):
                    # KeyboardInterrupt/SystemExit must stop the worker,
                    # never count as a tenant error.
                    if op is not None:
                        op.finish(error=e)
                    with ledger_lock:
                        ledgers[cls].errors += 1
                finally:
                    queue.done()

        # Tune controller (the chaos+autotuner composition): the LIVE
        # admission cap is the "workers" knob, readahead/prefetch ride
        # their usual knobs, and the p99 guardrail samples the HIGHEST-
        # priority class's recorder — the controller defends the gold
        # SLO while chasing aggregate goodput.
        controller = None
        tune_stats = None
        tune_on = getattr(cfg, "tune", None) is not None and cfg.tune.enabled
        if tune_on:
            controller = _build_serve_controller(
                cfg, queue, pf, recorders[str(classes[0]["name"])],
                lambda: completed_bytes[0], flight,
            )

        threads = [
            threading.Thread(target=worker, args=(i,), name=f"serve-{i}",
                             daemon=True)
            for i in range(sc.workers)
        ]
        activation = flight.activate() if flight is not None else None
        t0 = time.perf_counter_ns()
        try:
            if activation is not None:
                activation.__enter__()
            for t in threads:
                t.start()
            if controller is not None:
                controller.start()
            # ---- the open loop: replay the schedule in real time ----
            for req, gap in zip(schedule, gaps):
                if gap > 0:
                    time.sleep(gap)
                req.enqueue_ns = time.perf_counter_ns()
                if pf is not None:
                    pf.advance(req.index)
                queue.push(req)  # queue-overload sheds note via on_shed
            # Grace: let in-flight work drain, bounded — an overloaded
            # queue must not extend the run forever (that would be
            # closed-loop completion semantics sneaking back in).
            grace_s = max(1.0, 2.0 * scale)
            t_end = time.monotonic() + grace_s
            while (queue.queued or queue.in_service) \
                    and time.monotonic() < t_end:
                time.sleep(0.005)
        finally:
            drained = queue.close()  # leftovers shed as "drain"
            for t in threads:
                t.join(timeout=5.0)
            if controller is not None:
                tune_stats = controller.stop()
            if pf is not None:
                pf.close()
            if activation is not None:
                activation.__exit__(None, None, None)
            if tel is not None:
                tel.set_chips(1)
                tel_summary = tel.close()
        wall = (time.perf_counter_ns() - t0) / 1e9
        cache.close()

        # Merge the queue's shed ledger into the per-class ledgers
        # (shed-during-drain — `drained` leftovers at the bell — is a
        # real SLO miss and counts like any other shed).
        qstats = queue.stats()
        qstats["drained_at_close"] = drained
        for reason, by_cls in qstats["shed"].items():
            for cls, n in by_cls.items():
                if cls in ledgers:
                    ledgers[cls].shed += n

        serve_extra = self._scorecard(
            schedule, ledgers, recorders, tenant_bytes, qstats,
            wall, completed_bytes[0], classes,
        )
        if pf is not None:
            serve_extra["prefetch"] = pf.stats()
        serve_extra["cache"] = cache.stats()

        summaries = {}
        if len(agg_rec):
            summaries["request"] = summarize_ns(agg_rec.as_ns_array())
        for cls, rec in recorders.items():
            if len(rec):
                summaries[f"request_{cls}"] = summarize_ns(rec.as_ns_array())
        gbps = (completed_bytes[0] / 1e9) / wall if wall > 0 else 0.0
        errors = sum(led.errors for led in ledgers.values())
        res = RunResult(
            workload="serve",
            config=cfg.to_dict(),
            bytes_total=completed_bytes[0],
            wall_seconds=wall,
            gbps=gbps,
            gbps_per_chip=gbps,
            n_chips=1,
            summaries=summaries,
            errors=errors,
        )
        res.extra["serve"] = serve_extra
        if tune_stats is not None:
            res.extra["tune"] = tune_stats
        if tel_summary is not None:
            res.extra["telemetry"] = tel_summary
        from tpubench.storage.tail import collect_tail_stats

        tail_stats = collect_tail_stats(self.backend)
        if tail_stats:
            res.extra["tail"] = tail_stats
        if flight is not None:
            res.extra["flight"] = flight.summary()
            if jpath_stream:
                from tpubench.replay.bundle import journal_replay_stamp

                s = summaries.get("request")
                res.extra["flight_journal"] = flight.write_journal(
                    jpath_stream,
                    extra={
                        "workload": "serve", "n_chips": 1,
                        # The replay stamp: everything `tpubench record`
                        # needs to rebuild this run as a bundle. Rate is
                        # the EFFECTIVE offered load (sweep points
                        # override the config's).
                        "replay": journal_replay_stamp(
                            cfg, schedule, objects, serve_extra,
                            rate_rps=(
                                self.rate_rps
                                if self.rate_rps is not None
                                else sc.rate_rps
                            ),
                            errors=errors,
                            p99_ms=s.p99_ms if s is not None else None,
                            source=self.replay_source,
                        ),
                    },
                    max_bytes=cfg.obs.journal_max_bytes,
                )
        return res

    def _scorecard(self, schedule, ledgers, recorders, tenant_bytes,
                   qstats, wall, completed_bytes, classes) -> dict:
        return serve_scorecard(
            self.cfg.serve, schedule, ledgers, recorders, tenant_bytes,
            qstats, wall, completed_bytes, classes,
        )


def serve_scorecard(sc, schedule, ledgers, recorders, tenant_bytes,
                    qstats, wall, completed_bytes, classes) -> dict:
    """The per-class serve scorecard (``extra["serve"]``), shared by the
    single-host and elastic-pod planes — the A/B between them must never
    come from scorecard-math drift."""
    per_class = {}
    for c in classes:
        cls = str(c["name"])
        led = ledgers[cls]
        rec = recorders[cls]
        arr = rec.as_ns_array()
        per_class[cls] = {
            "priority": int(c.get("priority", 0)),
            "weight": float(c.get("weight", 1.0)),
            "deadline_ms": float(c["deadline_ms"]),
            "arrivals": led.arrivals,
            "completed": led.completed,
            "deadline_met": led.deadline_met,
            "shed": led.shed,
            "errors": led.errors,
            "bytes": led.bytes,
            "slo_attainment": led.slo_attainment(),
            "p50_ms": float(np.percentile(arr, 50) / 1e6)
            if arr.size else None,
            "p99_ms": float(np.percentile(arr, 99) / 1e6)
            if arr.size else None,
        }
    # Jain fairness over weight-normalized per-TENANT goodput:
    # tenants that sent traffic compete; a starved tenant's 0 is a
    # legitimate unfairness sample (zero-completed ≠ excluded).
    # Weights come off the schedule's own Request objects — never a
    # build_tenants re-derivation that must stay bit-identical.
    weights = {r.tenant.name: r.tenant.weight for r in schedule}
    norm = [
        tenant_bytes.get(name, 0) / w
        for name, w in sorted(weights.items())
    ]
    arrivals = len(schedule)
    completed = sum(led.completed for led in ledgers.values())
    shed = sum(led.shed for led in ledgers.values())
    return {
        "qos": sc.qos,
        "arrival": sc.arrival,
        "tenants": sc.tenants,
        "active_tenants": len(weights),
        "duration_s": sc.duration_s,
        "wall_s": wall,
        "offered_rps": arrivals / wall if wall > 0 else None,
        "achieved_rps": completed / wall if wall > 0 else None,
        "arrivals": arrivals,
        "completed": completed,
        "shed": shed,
        "shed_by_reason": qstats["shed"],
        "goodput_gbps": (completed_bytes / 1e9) / wall
        if wall > 0 else 0.0,
        "jain_fairness": jain_index(norm),
        "queue": {
            k: qstats[k] for k in (
                "cap", "queue_limit", "peak_queue", "peak_in_service",
            )
        },
        "classes": per_class,
    }


def _merge_windows(windows: list) -> list:
    """Merge overlapping [t0, t1] intervals (the resize windows the
    scorecard brackets events with)."""
    out: list = []
    for w0, w1 in sorted(windows):
        if out and w0 <= out[-1][1]:
            out[-1][1] = max(out[-1][1], w1)
        else:
            out.append([w0, w1])
    return out


def _in_windows(t: float, windows: list) -> bool:
    return any(w0 <= t < w1 for w0, w1 in windows)


class _ElasticServe:
    """The serve plane fanned across an N-host hermetic threaded pod
    with ELASTIC membership: every miss routes through coop-cache
    consistent-hash ownership over a shared loopback fabric, and the
    ``serve.membership_timeline`` changes the pod's shape mid-run —
    hosts die (``kill_host``: no goodbye, peers fall back to origin
    through the PeerMissError/retry composition), leave cooperatively
    (``leave_host``: the warm-handoff protocol drains the departing
    owner's hot set to the chunks' new owners over the peer channel),
    stall (``pause_host``: transient peer errors for the window) and
    come back clean (``rejoin_host``). Membership events ride the
    dispatcher's own schedule walk (virtual time), so runs replay
    bit-identically for a seed; the resize scorecard lands in
    ``extra["membership"]``.

    In-flight requests against a dead host FAIL OVER at pop time (the
    worker re-targets a live host) — the admission queue never wedges
    on a death. Dispatch targets only live hosts; the pod completing
    with zero live hosts is the (counted) degenerate error case."""

    def __init__(self, cfg: BenchConfig, backend: StorageBackend,
                 rate_rps: Optional[float],
                 replay_source: Optional[dict] = None):
        self.cfg = cfg
        self.backend = backend
        self.rate_rps = rate_rps
        self.replay_source = replay_source

    def run(self) -> RunResult:
        # Lazy elastic-plane imports: the single-host serve path (and
        # `tpubench report`, which imports this module for rendering)
        # must not pay for them.
        from tpubench.dist.membership import ElasticFabric, remap_stats
        from tpubench.mem.slab import (
            CopyMeter,
            SlabPool,
            release_payload,
        )
        from tpubench.pipeline.coop import CoopCache, LoopbackChannel
        from tpubench.storage.base import StorageError

        cfg, sc = self.cfg, self.cfg.serve
        if getattr(cfg, "tune", None) is not None and cfg.tune.enabled:
            # Loud, not silent: the serve tune controller actuates the
            # single-host plane's knobs — running it disconnected would
            # hand a tuning user arms that never moved.
            raise SystemExit(
                "serve: --tune does not compose with the elastic pod "
                "(serve.hosts > 1) yet — run the autotuner on the "
                "single-host plane"
            )
        chunk = sc.chunk_bytes or cfg.workload.granule_bytes
        objects = self.backend.list(cfg.workload.object_name_prefix)
        schedule = build_schedule(cfg, self.backend, self.rate_rps,
                                  objects=objects)
        tlabel = transport_label(cfg)
        scale = parse_sleep_scale("serve arrival gaps")
        gaps = scaled_gaps([r.arrival_s for r in schedule], scale)

        qos = sc.qos
        budgets = class_budget_split(sc.classes, cfg.pipeline.cache_bytes) \
            if qos else None
        flight = flight_from_config(cfg)
        shed_log = _ShedLog(flight, tlabel)

        # Per-request SLO outcome, indexed by schedule position: True =
        # completed within deadline, False = late/shed/error, None =
        # never resolved (counts as a miss). The resize-vs-steady SLO
        # split is computed from this against the event windows.
        outcome: list = [None] * len(schedule)

        def on_shed(req: Request, reason: str) -> None:
            outcome[req.index] = False
            shed_log(req, reason)

        queue = AdmissionQueue(
            cap=sc.admission_cap or sc.workers, qos=qos,
            queue_limit=(sc.queue_limit or 8 * sc.workers) if qos else 0,
            on_shed=on_shed,
        )
        worker_flights = [
            flight.worker(f"serve-{i}") if flight is not None else None
            for i in range(sc.workers)
        ]

        # ---- the pod: N hosts over one membership-aware fabric ------
        vnow = [0.0]  # virtual schedule time, driven by the dispatcher
        fabric = ElasticFabric(
            sc.hosts, vnodes=cfg.coop.vnodes, clock=lambda: vnow[0],
            flight_ring=(
                flight.worker("member") if flight is not None else None
            ),
        )
        pc = cfg.pipeline
        use_pool = pc.slab_pool and chunk > 0
        slab_bytes = max(chunk, pc.slab_bytes)
        pool_slabs = pc.pool_slabs or 64
        hosts: dict[int, dict] = {}
        for h in range(sc.hosts):
            pool = (
                SlabPool(slab_bytes, pool_slabs, use_native=False)
                if use_pool else None
            )
            meter = CopyMeter()
            cache = ChunkCache(pc.cache_bytes, owner_budgets=budgets)

            def origin_fetch(key, _pool=pool, _meter=meter):
                return fetch_chunk(
                    self.backend, key, pool=_pool, meter=_meter
                )

            coop = CoopCache(
                cache,
                host_id=h,
                ring=fabric.ring,
                channel=LoopbackChannel(fabric.broker, h),
                origin_fetch=origin_fetch,
                pool=pool,
                meter=meter,
                enabled=True,
                peer_budget_bytes=cfg.coop.peer_budget_bytes,
                retry_cfg=cfg.transport.retry,
                flight_recorder=flight,
            )
            fabric.add_host(coop)
            hosts[h] = {"coop": coop, "cache": cache, "pool": pool,
                        "meter": meter}

        # ---- membership plan + resize windows (virtual seconds) -----
        member_plan: list = []  # (t, action, host)
        windows: list = []
        for t0, t1, spec in sc.membership_timeline:
            (action, host), = spec.items()
            t0, t1 = float(t0), float(t1)
            if action == "pause_host":
                member_plan.append((t0, "pause_host", int(host)))
                member_plan.append((t1, "resume_host", int(host)))
                windows.append([t0, t1 + sc.resize_window_s])
            else:
                member_plan.append((t0, action, int(host)))
                windows.append([t0, t0 + sc.resize_window_s])
        member_plan.sort(key=lambda e: e[0])
        windows = _merge_windows(windows)

        uniq_keys = list({r.key for r in schedule})
        events_out: list = []
        snapshots: list = []  # (t_virtual, aggregate-counter dict)

        classes = sorted(
            sc.classes, key=lambda c: int(c.get("priority", 0))
        )
        ledgers = {str(c["name"]): ClassLedger() for c in classes}
        recorders = {
            str(c["name"]): LatencyRecorder(f"request_{c['name']}")
            for c in classes
        }
        agg_rec = LatencyRecorder("request")
        ledger_lock = threading.Lock()
        tenant_bytes: dict[str, int] = {}
        completed_bytes = [0]
        failovers = [0]
        no_live_host_errors = [0]

        for req in schedule:
            ledgers[req.tenant.cls].arrivals += 1

        def take_snapshot(t: float) -> None:
            agg = fabric.aggregate()
            with ledger_lock:
                agg["completed"] = sum(
                    led.completed for led in ledgers.values()
                )
            snapshots.append((t, agg))

        def apply_event(t: float, action: str, host: int) -> None:
            vnow[0] = max(vnow[0], t)
            before = fabric.owners_of(uniq_keys)
            handoff = None
            if action == "kill_host":
                ok = fabric.kill_host(host)
            elif action == "leave_host":
                handoff = fabric.leave_host(host)
                ok = handoff is not None
            elif action == "pause_host":
                ok = fabric.pause_host(host)
            elif action == "resume_host":
                ok = fabric.resume_host(host)
            elif action == "rejoin_host":
                ok = fabric.rejoin_host(host)
            else:  # unreachable under validate_membership_timeline
                ok = False
            ev = {
                "t_s": t, "action": action, "host": host, "applied": ok,
                "epoch": fabric.membership.epoch,
            }
            ev.update(remap_stats(
                uniq_keys, before, fabric.owners_of(uniq_keys)
            ))
            if handoff is not None:
                ev["handoff"] = handoff
            events_out.append(ev)
            take_snapshot(t)

        # ---- telemetry (the single-host wiring) ---------------------
        jpath_stream = None
        if cfg.obs.flight_journal:
            jpath_stream = host_journal_path(
                cfg.obs.flight_journal, cfg.dist.process_id,
                cfg.dist.num_processes,
            )
        tel = telemetry_from_config(cfg)
        tel_summary = None
        if tel is not None:
            tel.resource["workload"] = "serve"
            if flight is not None:
                tel.attach_flight(flight)
                if jpath_stream:
                    tel.stream_journal(
                        flight, jpath_stream,
                        extra_fn=lambda: {"workload": "serve"},
                        max_bytes=cfg.obs.journal_max_bytes,
                    )
            tel.attach_recorders([agg_rec])
            tel.start()

        def worker(i: int) -> None:
            wf = worker_flights[i]
            while True:
                req = queue.pop()
                if req is None:
                    return
                cls = req.tenant.cls
                t_pop = time.perf_counter_ns()
                op = None
                try:
                    host = req.host
                    if not fabric.is_dispatchable(host):
                        # The assigned front end died/paused while this
                        # request sat queued: fail over to a live host
                        # instead of wedging or erroring — exactly what
                        # a pod front door does.
                        live = sorted(fabric.live_hosts())
                        if not live:
                            with ledger_lock:
                                no_live_host_errors[0] += 1
                            raise StorageError(
                                "no live hosts in the pod",
                                transient=False,
                            )
                        host = live[req.index % len(live)]
                        with ledger_lock:
                            failovers[0] += 1
                    entry = hosts[host]
                    cache, coop = entry["cache"], entry["coop"]
                    data = cache.get(req.key)
                    if data is not None:
                        source = "hit"
                        if wf is not None:
                            op = wf.begin(
                                req.key.object, tlabel, kind="cache",
                                enqueue_ns=req.enqueue_ns,
                            )
                            op.mark("cache_hit")
                    else:
                        if wf is not None:
                            op = wf.begin(
                                req.key.object, tlabel,
                                enqueue_ns=req.enqueue_ns,
                            )
                            op.mark("cache_miss", t_pop)
                        data, source = cache.get_or_fetch_info(
                            req.key,
                            lambda k=req.key, c=coop: c.fetch(k),
                            owner=cls if qos else None,
                        )
                        if op is not None:
                            if source == "hit":
                                # Raced hit (the single-host plane's
                                # discipline): the would-be miss record
                                # becomes a cache record so the fetcher
                                # stays the only byte-carrying one.
                                op.abandon()
                                op = wf.begin(
                                    req.key.object, tlabel, kind="cache",
                                    enqueue_ns=req.enqueue_ns,
                                )
                                op.mark("cache_hit")
                            else:
                                op.mark("body_complete")
                    done_ns = time.perf_counter_ns()
                    met = done_ns <= req.deadline_ns
                    nbytes = len(data)
                    release_payload(data)  # consumer lease ref, if any
                    if op is not None:
                        op.note(
                            "serve_req", cls=cls, outcome="completed",
                            deadline_met=met, host=host,
                        )
                        op.finish(
                            nbytes if source in ("hit", "fetched") else 0
                        )
                    lat_ns = done_ns - req.enqueue_ns
                    with ledger_lock:
                        led = ledgers[cls]
                        led.completed += 1
                        led.bytes += nbytes
                        if met:
                            led.deadline_met += 1
                        tenant_bytes[req.tenant.name] = (
                            tenant_bytes.get(req.tenant.name, 0) + nbytes
                        )
                        completed_bytes[0] += nbytes
                    outcome[req.index] = bool(met)
                    recorders[cls].record_ns(lat_ns)
                    agg_rec.record_ns(lat_ns)
                except Exception as e:  # noqa: BLE001 — per-request domain
                    # The single-host plane's rule: one tenant's failed
                    # fetch is its ledger's error, never a run abort;
                    # KeyboardInterrupt/SystemExit still stop the run.
                    if op is not None:
                        op.finish(error=e)
                    outcome[req.index] = False
                    with ledger_lock:
                        ledgers[req.tenant.cls].errors += 1
                finally:
                    queue.done()

        threads = [
            threading.Thread(target=worker, args=(i,),
                             name=f"serve-{i}", daemon=True)
            for i in range(sc.workers)
        ]
        activation = flight.activate() if flight is not None else None
        t0 = time.perf_counter_ns()
        try:
            if activation is not None:
                activation.__enter__()
            for t in threads:
                t.start()
            take_snapshot(0.0)
            # ---- the open loop, with membership events interleaved --
            mp_i = 0
            snap_every = max(1, len(schedule) // 64)
            rr = 0
            for req, gap in zip(schedule, gaps):
                while (mp_i < len(member_plan)
                       and member_plan[mp_i][0] <= req.arrival_s):
                    apply_event(*member_plan[mp_i])
                    mp_i += 1
                if gap > 0:
                    time.sleep(gap)
                vnow[0] = max(vnow[0], req.arrival_s)
                live = sorted(fabric.live_hosts())
                req.host = live[rr % len(live)] if live else -1
                rr += 1
                req.enqueue_ns = time.perf_counter_ns()
                queue.push(req)
                if rr % snap_every == 0:
                    take_snapshot(req.arrival_s)
            while mp_i < len(member_plan):  # events past the last arrival
                apply_event(*member_plan[mp_i])
                mp_i += 1
            grace_s = max(1.0, 2.0 * scale)
            t_end = time.monotonic() + grace_s
            while (queue.queued or queue.in_service) \
                    and time.monotonic() < t_end:
                time.sleep(0.005)
        finally:
            drained = queue.close()
            for t in threads:
                t.join(timeout=5.0)
            take_snapshot(max(vnow[0], sc.duration_s))
            if activation is not None:
                activation.__exit__(None, None, None)
            if tel is not None:
                tel.set_chips(1)
                tel_summary = tel.close()
        wall = (time.perf_counter_ns() - t0) / 1e9

        # ---- teardown: coops, caches, pools (leak detection) --------
        per_host = []
        pool_leaks = 0
        fabric.close()
        for h, entry in sorted(hosts.items()):
            stats = {"host": h, "coop": entry["coop"].stats(),
                     "cache": entry["cache"].stats(),
                     "copies": entry["meter"].stats()}
            entry["cache"].close()
            if entry["pool"] is not None:
                ps = entry["pool"].close()
                pool_leaks += ps.get("leaked_slabs", 0)
                stats["pool"] = ps
            per_host.append(stats)

        qstats = queue.stats()
        qstats["drained_at_close"] = drained
        for reason, by_cls in qstats["shed"].items():
            for cls, n in by_cls.items():
                if cls in ledgers:
                    ledgers[cls].shed += n

        serve_extra = serve_scorecard(
            sc, schedule, ledgers, recorders, tenant_bytes, qstats,
            wall, completed_bytes[0], classes,
        )
        membership = self._membership_scorecard(
            schedule, outcome, events_out, windows, snapshots, per_host,
            failovers[0], no_live_host_errors[0], pool_leaks, classes,
            fabric,
        )

        summaries = {}
        if len(agg_rec):
            summaries["request"] = summarize_ns(agg_rec.as_ns_array())
        for cls, rec in recorders.items():
            if len(rec):
                summaries[f"request_{cls}"] = summarize_ns(
                    rec.as_ns_array()
                )
        gbps = (completed_bytes[0] / 1e9) / wall if wall > 0 else 0.0
        errors = sum(led.errors for led in ledgers.values())
        res = RunResult(
            workload="serve",
            config=cfg.to_dict(),
            bytes_total=completed_bytes[0],
            wall_seconds=wall,
            gbps=gbps,
            gbps_per_chip=gbps,
            n_chips=1,
            summaries=summaries,
            errors=errors,
        )
        res.extra["serve"] = serve_extra
        res.extra["membership"] = membership
        if tel_summary is not None:
            res.extra["telemetry"] = tel_summary
        from tpubench.storage.tail import collect_tail_stats

        tail_stats = collect_tail_stats(self.backend)
        if tail_stats:
            res.extra["tail"] = tail_stats
        if flight is not None:
            res.extra["flight"] = flight.summary()
            if jpath_stream:
                from tpubench.replay.bundle import journal_replay_stamp

                s = summaries.get("request")
                res.extra["flight_journal"] = flight.write_journal(
                    jpath_stream,
                    extra={
                        "workload": "serve", "n_chips": 1,
                        # The single-host plane's stamp, plus the
                        # membership scorecard so the bundle baseline
                        # carries rewarm/failover numbers.
                        "replay": journal_replay_stamp(
                            cfg, schedule, objects, serve_extra,
                            rate_rps=(
                                self.rate_rps
                                if self.rate_rps is not None
                                else sc.rate_rps
                            ),
                            membership=membership,
                            errors=errors,
                            p99_ms=s.p99_ms if s is not None else None,
                            source=self.replay_source,
                        ),
                    },
                    max_bytes=cfg.obs.journal_max_bytes,
                )
        return res

    # ----------------------------------------------------- scorecard --
    def _membership_scorecard(self, schedule, outcome, events_out,
                              windows, snapshots, per_host, failovers,
                              no_live_host_errors, pool_leaks, classes,
                              fabric) -> dict:
        return membership_scorecard(
            self.cfg.serve, schedule, outcome, events_out, windows,
            snapshots, per_host, failovers, no_live_host_errors,
            pool_leaks, classes, fabric,
        )


def membership_scorecard(sc, schedule, outcome, events_out,
                         windows, snapshots, per_host, failovers,
                         no_live_host_errors, pool_leaks, classes,
                         fabric) -> dict:
    """The resize scorecard (``extra["membership"]``), shared by the
    elastic serve plane and the incident drill (workloads/drill.py) —
    their A/B must never come from scorecard-math drift."""
    # Per-class SLO, resize windows vs steady state — by ARRIVAL
        # time (the open-loop convention: the system owns everything
        # that arrived in the window, including what it shed).
    split: dict = {"resize": {}, "steady": {}}
    counts = {"resize": 0, "steady": 0}
    tally: dict = {}
    for req in schedule:
        seg = "resize" if _in_windows(req.arrival_s, windows) \
            else "steady"
        counts[seg] += 1
        met, tot = tally.get((seg, req.tenant.cls), (0, 0))
        tally[(seg, req.tenant.cls)] = (
            met + (1 if outcome[req.index] else 0), tot + 1
        )
    for c in classes:
        cls = str(c["name"])
        for seg in ("resize", "steady"):
            met, tot = tally.get((seg, cls), (0, 0))
            split[seg][cls] = (met / tot) if tot else None

    # Counter series helpers over the (virtual-time, aggregate)
    # snapshots: value at t = the last snapshot at or before t.
    def value_at(t: float, key: str) -> int:
        v = 0
        for st, agg in snapshots:
            if st <= t:
                v = agg.get(key, 0)
            else:
                break
        return v

    total_origin = snapshots[-1][1].get("origin_bytes", 0) \
        if snapshots else 0
    # Clip windows to the run's virtual span for the byte/length
    # split: an event near the bell opens a window that extends
    # past end-of-run, and charging that phantom tail would both
    # shrink steady_len and inflate steady_rate_bps — exactly the
    # comparison this block exists to keep honest.
    clipped = [
        (min(w0, sc.duration_s), min(w1, sc.duration_s))
        for w0, w1 in windows
    ]
    window_origin = sum(
        value_at(w1, "origin_bytes") - value_at(w0, "origin_bytes")
        for w0, w1 in clipped
    )
    window_len = sum(w1 - w0 for w0, w1 in clipped)
    steady_len = max(0.0, sc.duration_s - window_len)
    steady_origin = max(0, total_origin - window_origin)
    steady_rate = steady_origin / steady_len if steady_len > 0 \
        else None

    # Time-to-rewarm per view-changing event: first post-event
    # snapshot window whose peer-hit ratio is back to >= 90% of the
    # cumulative pre-event ratio.
    def ratio(agg: dict) -> Optional[float]:
        req = agg.get("peer_requests", 0)
        return agg.get("peer_hits", 0) / req if req else None

    for ev in events_out:
        if ev["action"] not in (
            "kill_host", "leave_host", "pause_host",
        ):
            continue
        te = ev["t_s"]
        pre = None
        for st, agg in snapshots:
            if st <= te:
                pre = ratio(agg)
            else:
                break
        ev["pre_event_peer_hit_ratio"] = pre
        rewarm = None
        if pre:
            prev = None
            for st, agg in snapshots:
                if st < te:
                    continue
                if prev is not None:
                    dreq = (agg.get("peer_requests", 0)
                            - prev[1].get("peer_requests", 0))
                    dhit = (agg.get("peer_hits", 0)
                            - prev[1].get("peer_hits", 0))
                    if dreq > 0 and dhit / dreq >= 0.9 * pre:
                        rewarm = max(0.0, st - te)
                        break
                prev = (st, agg)
        ev["time_to_rewarm_s"] = rewarm

    agg = fabric.aggregate()
    final_ratio = ratio(agg)
    return {
        "hosts": sc.hosts,
        "epoch": agg["epoch"],
        "resize_window_s": sc.resize_window_s,
        "events": events_out,
        "windows_s": [list(w) for w in windows],
        "slo": split,
        "arrivals": counts,
        "origin_bytes": {
            "total": total_origin,
            "resize_windows": window_origin,
            "steady": steady_origin,
            "steady_rate_bps": steady_rate,
        },
        "handoff": {
            "out_chunks": agg["handoff_out_chunks"],
            "out_bytes": agg["handoff_out_bytes"],
            "in_chunks": agg["handoff_in_chunks"],
            "in_bytes": agg["handoff_in_bytes"],
            "rejects": agg["handoff_rejects"],
        },
        "peer_hit_ratio": final_ratio,
        "pod_coalesced": agg["pod_coalesced"],
        "failovers": failovers,
        "no_live_host_errors": no_live_host_errors,
        "pool_leaked_slabs": pool_leaks,
        "per_host": per_host,
    }


def _build_serve_controller(cfg, queue, pf, guard_rec, bytes_fn, flight):
    """Serve-plane tune controller: admission cap (the "workers" knob —
    the PR-5 hook, live via AdmissionQueue.set_cap), readahead depth and
    prefetch fan-out; objective is aggregate goodput, guardrail is the
    HIGHEST-priority class's p99."""
    from tpubench.tune.controller import (
        Knob,
        RecorderSampler,
        TuneController,
        readahead_ceiling,
    )

    wanted = set(cfg.tune.knobs)
    knobs = []
    if "workers" in wanted:
        knobs.append(Knob(
            "workers", queue.cap, queue.set_cap,
            lo=1, hi=max(2, cfg.serve.workers), mode="mul",
        ))
    if "readahead" in wanted and pf is not None:
        knobs.append(Knob(
            "readahead", cfg.serve.readahead,
            lambda v: pf.reclamp(depth=v),
            lo=1, hi=readahead_ceiling(cfg.serve.readahead), mode="mul",
        ))
    if "prefetch_workers" in wanted and pf is not None:
        hi = pf.stats()["workers_max"]
        if hi > 1:
            knobs.append(Knob(
                "prefetch_workers", pf.active_workers, pf.set_workers,
                lo=1, hi=hi, mode="add",
            ))
    if not knobs:
        return None
    sampler = RecorderSampler([guard_rec], bytes_fn)
    ring = flight.worker("tune") if flight is not None else None
    return TuneController(cfg.tune, knobs, sampler, flight_ring=ring)


def run_serve_sweep(cfg: BenchConfig, tracer=None) -> RunResult:
    """``tpubench serve --serve-sweep``: step offered load through
    ``serve.sweep_points × rate_rps`` and emit the latency-vs-load curve
    with the saturation knee identified (p99 inflection / goodput
    saturation) — the Pulsar-methodology sweep, hermetic on the fake
    backend."""
    validate_serve_config(cfg.serve)
    points = []
    results = []
    for mult in cfg.serve.sweep_points:
        c = BenchConfig.from_dict(cfg.to_dict())
        if cfg.serve.sweep_duration_s > 0:
            c.serve.duration_s = cfg.serve.sweep_duration_s
        # Per-point endpoint churn off (the tune-sweep policy): one
        # sweep must not bind N telemetry ports.
        c.telemetry.port = -1
        c.telemetry.enabled = False
        c.telemetry.otlp = False
        if c.obs.flight_journal:
            # One journal PER POINT (.pt<i> suffix): every point writes
            # the same configured path otherwise, and the sweep's
            # journal would silently hold only the heaviest point.
            c.obs.flight_journal = f"{c.obs.flight_journal}.pt{len(points)}"
        res = run_serve(
            c, rate_rps=cfg.serve.rate_rps * mult, tracer=tracer
        )
        sv = res.extra["serve"]
        gold = min(
            sv["classes"].values(), key=lambda x: x["priority"]
        ) if sv["classes"] else {}
        s = res.summaries.get("request")
        points.append({
            "multiplier": mult,
            "offered_rps": sv["offered_rps"],
            "achieved_rps": sv["achieved_rps"],
            "goodput_gbps": sv["goodput_gbps"],
            "p99_ms": s.p99_ms if s is not None else None,
            "gold_p99_ms": gold.get("p99_ms"),
            "gold_slo_attainment": gold.get("slo_attainment"),
            "shed": sv["shed"],
            "jain_fairness": sv["jain_fairness"],
        })
        results.append(res)
    knee = find_knee(points)
    # The sweep's RunResult carries the heaviest point's latencies plus
    # the whole curve; `tpubench report` renders curve + knee.
    last = results[-1]
    res = RunResult(
        workload="serve",
        config=cfg.to_dict(),
        bytes_total=sum(r.bytes_total for r in results),
        wall_seconds=sum(r.wall_seconds for r in results),
        gbps=last.gbps,
        gbps_per_chip=last.gbps,
        n_chips=1,
        summaries=last.summaries,
        errors=sum(r.errors for r in results),
    )
    res.extra["serve"] = {
        "qos": cfg.serve.qos,
        "sweep": {
            "base_rate_rps": cfg.serve.rate_rps,
            "points": points,
            "knee": knee,
        },
    }
    return res


# -------------------------------------------------------------- rendering --


def format_serve_scorecard(sv: dict) -> str:
    """Human rendering of ``extra["serve"]`` (CLI + ``tpubench report``)."""
    sweep = sv.get("sweep")
    if sweep:
        lines = ["== serve load sweep =="]
        lines.append(
            f"  base rate={sweep.get('base_rate_rps', 0):.0f} rps  "
            f"qos={'on' if sv.get('qos') else 'off'}"
        )
        lines.append(
            "  offered_rps  achieved_rps  goodput  p99_ms  gold_p99  shed"
        )
        for p in sweep.get("points", ()):
            lines.append(
                f"  {p.get('offered_rps') or 0:11.1f}"
                f"  {p.get('achieved_rps') or 0:12.1f}"
                f"  {p.get('goodput_gbps') or 0:7.4f}"
                f"  {p.get('p99_ms') or 0:6.1f}"
                f"  {p.get('gold_p99_ms') or 0:8.1f}"
                f"  {p.get('shed', 0):4d}"
            )
        knee = sweep.get("knee")
        if knee:
            lines.append(
                f"  knee: {knee['offered_rps']:.1f} rps "
                f"({knee['reason']}, point {knee['index']})"
            )
        else:
            lines.append("  knee: not reached in this sweep")
        return "\n".join(lines)
    lines = [
        "== serve scorecard ==",
        (
            f"  qos={'on' if sv.get('qos') else 'off'} "
            f"arrival={sv.get('arrival', '?')} "
            f"tenants={sv.get('active_tenants', 0)}"
            f"/{sv.get('tenants', 0)}  "
            f"offered={sv.get('offered_rps') or 0:.1f} rps "
            f"achieved={sv.get('achieved_rps') or 0:.1f} rps "
            f"goodput={sv.get('goodput_gbps', 0.0):.4f} GB/s"
        ),
        (
            f"  arrivals={sv.get('arrivals', 0)} "
            f"completed={sv.get('completed', 0)} "
            f"shed={sv.get('shed', 0)} "
            + (
                f"jain={sv['jain_fairness']:.3f}"
                if sv.get("jain_fairness") is not None else "jain=n/a"
            )
        ),
    ]
    for cls, st in (sv.get("classes") or {}).items():
        slo = st.get("slo_attainment")
        p99 = st.get("p99_ms")
        lines.append(
            f"  [{cls}] prio={st.get('priority')} "
            f"deadline={st.get('deadline_ms', 0):.0f}ms "
            f"arrivals={st.get('arrivals', 0)} "
            f"completed={st.get('completed', 0)} "
            f"shed={st.get('shed', 0)} "
            f"slo={f'{slo:.1%}' if slo is not None else 'n/a'} "
            f"p99={f'{p99:.1f}ms' if p99 is not None else 'n/a'}"
        )
    q = sv.get("queue")
    if q:
        lines.append(
            f"  queue: cap={q.get('cap')} limit={q.get('queue_limit')} "
            f"peak={q.get('peak_queue')} "
            f"peak_in_service={q.get('peak_in_service')}"
        )
    return "\n".join(lines)


def format_membership_scorecard(mb: dict) -> str:
    """Human rendering of ``extra["membership"]`` — the resize scorecard
    (CLI + ``tpubench report``)."""
    lines = [
        "== membership resize scorecard ==",
        (
            f"  pod: {mb.get('hosts', 0)} hosts  "
            f"final epoch={mb.get('epoch', 0)}  "
            f"failovers={mb.get('failovers', 0)}  "
            f"leaked_slabs={mb.get('pool_leaked_slabs', 0)}"
        ),
    ]
    for ev in mb.get("events", ()):
        extra = ""
        ho = ev.get("handoff")
        if ho:
            extra = (
                f"  handoff={ho.get('chunks', 0)} chunks/"
                f"{ho.get('bytes', 0)}B"
            )
        rw = ev.get("time_to_rewarm_s")
        if rw is not None:
            extra += f"  rewarm={rw:.2f}s"
        lines.append(
            f"  [t={ev.get('t_s', 0.0):.2f}s] {ev.get('action')} "
            f"host {ev.get('host')} -> epoch {ev.get('epoch')} "
            f"(remap {ev.get('remap_fraction', 0.0):.1%} = "
            f"{ev.get('remap_bytes', 0)}B){extra}"
        )
    slo = mb.get("slo") or {}
    for seg in ("resize", "steady"):
        cells = []
        for cls, v in (slo.get(seg) or {}).items():
            cells.append(
                f"{cls}={v:.1%}" if v is not None else f"{cls}=n/a"
            )
        arr = (mb.get("arrivals") or {}).get(seg, 0)
        lines.append(
            f"  SLO {seg:<6} ({arr} arrivals): " + " ".join(cells)
        )
    ob = mb.get("origin_bytes") or {}
    lines.append(
        f"  origin bytes: resize_windows={ob.get('resize_windows', 0)} "
        f"steady={ob.get('steady', 0)} total={ob.get('total', 0)}"
    )
    ho = mb.get("handoff") or {}
    phr = mb.get("peer_hit_ratio")
    lines.append(
        f"  handoff: out={ho.get('out_chunks', 0)} chunks/"
        f"{ho.get('out_bytes', 0)}B in={ho.get('in_chunks', 0)} chunks/"
        f"{ho.get('in_bytes', 0)}B rejects={ho.get('rejects', 0)}  "
        + (
            f"peer_hit={phr:.1%}" if phr is not None else "peer_hit=n/a"
        )
    )
    return "\n".join(lines)
