"""``tpubench train-ingest`` — step-paced training-loop ingest with
data-stall accounting.

Every other tpubench workload issues cold, demand-driven reads: fetch
and consumption never overlap, which is exactly the effect that
dominates real input pipelines (MLPerf TPU-pod scaling attributes step
time cliffs to input stalls). This workload emulates the consumer side
of a training job — a step loop that each step consumes a *batch* of
chunks, stages them to HBM, then "computes" for a configurable synthetic
window — on top of the pipeline subsystem (host chunk cache + readahead
prefetcher), and measures what an input pipeline is actually for:

* **data-stall time per step** — the time the step loop spent blocked
  waiting for bytes that were not ready (p50/p99 per-step stall ms, and
  the stalled-step fraction over ``pipeline.stall_threshold_ms``);
* **cache hit ratio** — including the re-epoch pass, where a warm cache
  should serve repeats without touching storage;
* **prefetch efficiency** — prefetched-and-used vs wasted bytes.

The A/B that matters: the same run with ``pipeline.readahead=0`` (cold,
demand-only — the behavior of every pre-PR-3 workload) against
readahead on. Both arms go through the identical cache/fetch code path,
so the delta is the overlap, not incidental code differences.

Step records land in the flight journal as ``kind="step"`` with
``stall_begin``/``stall_end`` bracketing the step's data wait; chunk
accesses carry ``cache_hit``/``cache_miss``/``prefetch_issue`` phases —
``tpubench report timeline`` attributes stalls from the same journal.

Pod path (``pipeline.pod``): each step's batch is treated as one
sharded logical object — byte-range shards staged across the mesh and
reassembled over ICI (``dist.shard`` / ``dist.reassemble``) — instead
of the per-host slot-ring ``device_put`` path.
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional

import numpy as np

from tpubench.config import BenchConfig, validate_pipeline_config
from tpubench.mem.slab import (
    CopyMeter,
    SlabLease,
    SlabPool,
    payload_view,
    release_payload,
)
from tpubench.metrics.percentiles import summarize_ns
from tpubench.metrics.recorder import LatencyRecorder
from tpubench.metrics.report import RunResult
from tpubench.obs import tracing as _tracing
from tpubench.obs.flight import (
    flight_from_config,
    host_journal_path,
    transport_label,
)
from tpubench.obs.profiling import StepProfiler, parse_profile_steps
from tpubench.obs.telemetry import telemetry_from_config
from tpubench.pipeline.cache import ChunkCache, ChunkKey
from tpubench.pipeline.prefetch import Prefetcher, fetch_chunk
from tpubench.tune.controller import prefetch_workers_ceiling as _pf_ceiling
from tpubench.storage import open_backend
from tpubench.storage.base import StorageBackend, iter_ranges


def build_plan(cfg: BenchConfig, backend: StorageBackend) -> list[ChunkKey]:
    """One epoch's ordered chunk access plan: ``steps × batch_shards``
    chunk keys walked object-by-object (wrapping when the dataset is
    smaller than an epoch), each keyed by the object's CURRENT
    generation — an overwritten object yields new keys, and the cache
    invalidates the stale generation's chunks on first sight."""
    w, p = cfg.workload, cfg.pipeline
    chunk = p.chunk_bytes or w.granule_bytes
    n_objects = max(w.workers, w.threads, 1)
    needed = p.steps * p.batch_shards
    plan: list[ChunkKey] = []
    obj_chunks: list[list[ChunkKey]] = []
    for i in range(n_objects):
        name = f"{w.object_name_prefix}{i}"
        meta = backend.stat(name)
        obj_chunks.append([
            ChunkKey(w.bucket, name, meta.generation, start, length)
            for start, length in iter_ranges(meta.size, chunk)
        ])
        if sum(len(c) for c in obj_chunks) >= needed:
            break
    flat = [k for chunks in obj_chunks for k in chunks]
    if not flat:
        raise ValueError("train-ingest: dataset is empty (object_size=0?)")
    while len(plan) < needed:
        plan.extend(flat[: needed - len(plan)])
    return plan


def run_train_ingest(
    cfg: BenchConfig, backend: Optional[StorageBackend] = None
) -> RunResult:
    validate_pipeline_config(cfg.pipeline, staging=cfg.staging)
    p = cfg.pipeline
    chunk = p.chunk_bytes or cfg.workload.granule_bytes
    if p.readahead > 0 and p.cache_bytes < chunk:
        # Covers cache_bytes=0 through cache_bytes<chunk alike (this is
        # the ONLY guard — validate_pipeline_config runs for every
        # subcommand and must not reject non-pipeline workloads): every
        # prefetched chunk would hit the cache's oversize-skip path, be
        # counted as waste, and re-fetch on demand — ~2x the cold arm's
        # backend reads, silently. The effective chunk size is only
        # known here (chunk_bytes=0 defers to granule_bytes).
        raise SystemExit(
            f"pipeline.cache_bytes={p.cache_bytes} is smaller than one "
            f"chunk ({chunk} B) with readahead={p.readahead}: no "
            "prefetched chunk can ever be cached — raise --cache-bytes "
            "or set --readahead 0 (the cold arm)"
        )
    if p.readahead > 0 and 0 < p.readahead_bytes < chunk:
        # Sibling misconfiguration: a prefetch byte budget below one
        # chunk means advance() can never schedule anything — the
        # "readahead=N" arm would silently run cold and the A/B would
        # compare cold vs cold under different labels.
        raise SystemExit(
            f"pipeline.readahead_bytes={p.readahead_bytes} is smaller "
            f"than one chunk ({chunk} B): the prefetcher can never "
            "schedule a fetch — raise --readahead-bytes or drop it "
            "(0 = depth-bounded)"
        )
    if p.slab_pool and 0 < p.slab_bytes < chunk:
        # A slab that cannot hold one chunk makes every lease fail: the
        # run would degrade to errors, not to the bytes path. Rejected
        # here because the effective chunk size is only known now.
        raise SystemExit(
            f"pipeline.slab_bytes={p.slab_bytes} is smaller than one "
            f"chunk ({chunk} B): no chunk can be leased — raise "
            "--slab-bytes (or 0 = auto: one chunk per slab)"
        )
    owns_backend = backend is None
    backend = backend or open_backend(cfg)
    try:
        return _TrainIngest(cfg, backend).run()
    finally:
        if owns_backend:
            backend.close()


class _TrainIngest:
    def __init__(self, cfg: BenchConfig, backend: StorageBackend):
        self.cfg = cfg
        self.backend = backend

    # ------------------------------------------------------------ staging --
    def _make_stager(self):
        """Per-run staging sink (slot ring → device_put), or None for
        staging mode "none" / the pod path (which stages per step via
        dist.shard/reassemble)."""
        if self.cfg.staging.mode == "none" or self.cfg.pipeline.pod:
            return None
        from tpubench.staging.device import make_sink_factory

        factory = make_sink_factory(self.cfg)
        return factory(0) if factory is not None else None

    def _pod_setup(self):
        from tpubench.dist.reassemble import make_mesh, make_reassemble

        mesh = make_mesh(axis=self.cfg.dist.mesh_axis)
        return mesh, make_reassemble(mesh, self.cfg.dist.mesh_axis)

    def _pod_stage_gather(self, mesh, reassemble, datas: list):
        """Pod path for one step: the batch's bytes as byte-range shards
        across the mesh, reassembled over ICI. Returns gather-complete
        perf_counter_ns. ``datas`` holds payloads (bytes or slab leases);
        the shard build reads their views directly."""
        import jax

        from tpubench.dist.reassemble import shard_to_device_array
        from tpubench.dist.shard import ShardTable

        lane = self.cfg.staging.lane
        blob = b"".join(payload_view(d) for d in datas)
        n = int(mesh.devices.size)
        table = ShardTable.build(len(blob), n, align=lane)
        buffers = []
        for sh in table.shards():
            buf = np.zeros(table.shard_bytes, dtype=np.uint8)
            if sh.length:
                buf[: sh.length] = np.frombuffer(
                    blob[sh.start : sh.start + sh.length], dtype=np.uint8
                )
            buffers.append(buf)
        global_arr = shard_to_device_array(
            buffers, mesh, self.cfg.dist.mesh_axis, lane
        )
        jax.block_until_ready(global_arr)
        staged_ns = time.perf_counter_ns()
        gathered, _ = reassemble(global_arr)
        jax.block_until_ready(gathered)
        return staged_ns, time.perf_counter_ns()

    # ---------------------------------------------------------------- run --
    def run(self) -> RunResult:
        cfg, w, p = self.cfg, self.cfg.workload, self.cfg.pipeline
        plan_epoch = build_plan(cfg, self.backend)
        plan = plan_epoch * p.epochs
        batch = p.batch_shards
        total_steps = p.steps * p.epochs
        cache = ChunkCache(p.cache_bytes)
        # Zero-copy slab datapath (tpubench/mem/): chunks are leased from
        # a pinned-slab pool, readinto'd once off the wire, cached and
        # staged as views — the CopyMeter proves it (copies stamp below).
        meter = CopyMeter()
        pool: Optional[SlabPool] = None
        if p.slab_pool:
            chunk_eff = p.chunk_bytes or w.granule_bytes
            slab_bytes = p.slab_bytes or chunk_eff
            n_slabs = p.pool_slabs
            if not n_slabs:
                # Auto-size: the resident working set (cache budget in
                # CHUNKS — the cache accounts payload length, not slab
                # size — but never more than the plan's unique chunks) +
                # the readahead window + one step's batch + in-flight
                # fetch headroom. Overflow leases cover estimation error.
                resident = min(
                    p.cache_bytes // max(1, chunk_eff), len(set(plan))
                )
                n_slabs = min(
                    8192,
                    max(1, resident + p.readahead + batch
                        + p.prefetch_workers + 2),
                )
            pool = SlabPool(slab_bytes, n_slabs)
        tlabel = transport_label(cfg)
        flight = flight_from_config(cfg)
        consumer_wf = flight.worker("consumer") if flight is not None else None
        step_wf = flight.worker("steps") if flight is not None else None

        # Cooperative chunk cache (tpubench/pipeline/coop.py): misses
        # whose consistent-hash owner is a peer resolve over the peer
        # channel instead of origin; demand and prefetch misses alike
        # route through coop.fetch, so pod-wide single-flight covers
        # both. None when cfg.coop is off — the per-host baseline arm.
        from tpubench.pipeline.coop import coop_from_config

        def origin_fetch(key: ChunkKey):
            return fetch_chunk(self.backend, key, pool=pool, meter=meter)

        coop = coop_from_config(
            cfg, cache, origin_fetch, pool=pool, meter=meter, flight=flight,
        )
        if coop is not None and coop.lockstep and not (
            p.pod and p.readahead == 0
        ):
            # A lockstep (ICI) channel moves bytes by COLLECTIVES every
            # host must enter together, in the same order: only the
            # plan-synchronized pod demand path qualifies. Asynchronous
            # prefetch workers — or per-host cache divergence seeded by
            # readahead — desynchronize the broadcasts and hang the
            # mesh, so refuse loudly instead.
            raise SystemExit(
                "coop: the ici (lockstep) channel requires the "
                "plan-synchronized pod path (--pipeline-pod) with "
                "--readahead 0; use the request/reply channel for "
                "asynchronous consumers"
            )
        routed_fetch = coop.fetch if coop is not None else origin_fetch

        step_rec = LatencyRecorder("step")
        stall_rec = LatencyRecorder("stall")
        fetch_rec = LatencyRecorder("read")
        stalled_steps = 0
        consumed_bytes = 0
        compute_s = p.step_compute_ms / 1e3

        # Live telemetry (obs/telemetry.py): registry fed record-by-record
        # off the flight tap, demand-fetch latency sampled each tick, and
        # the journal streamed so `tpubench top` can watch the run.
        jpath_stream = None
        if cfg.obs.flight_journal:
            jpath_stream = host_journal_path(
                cfg.obs.flight_journal, cfg.dist.process_id,
                cfg.dist.num_processes,
            )
        tel = telemetry_from_config(cfg)
        if tel is not None:
            tel.resource["workload"] = "train_ingest"
            if flight is not None:
                tel.attach_flight(flight)
                if jpath_stream:
                    tel.stream_journal(
                        flight, jpath_stream,
                        extra_fn=lambda: {"workload": "train_ingest"},
                        max_bytes=cfg.obs.journal_max_bytes,
                    )
            tel.attach_recorders([fetch_rec])
            tel.start()

        # Step-windowed jax.profiler capture (obs/profiling.py): owns the
        # trace for this workload (the CLI's whole-run wrap steps aside);
        # defaults to the full step loop when no window is configured.
        prof_window = parse_profile_steps(cfg.obs.profile_steps) \
            or (0, total_steps - 1)
        profiler = StepProfiler(
            cfg.obs.profile_dir, prof_window[0], prof_window[1]
        )

        stager = self._make_stager()
        mesh = reassemble = None
        if p.pod:
            mesh, reassemble = self._pod_setup()
            # Warmup: the first reassemble pays compile; a step must not.
            # jit compiles PER SHAPE, so the warmup blob must be the size
            # of a real full batch (batch × chunk) — a token-sized blob
            # would shift the compile onto step 0 and skew its stall/step
            # percentiles (a short final batch may still recompile once).
            chunk = p.chunk_bytes or w.granule_bytes
            self._pod_stage_gather(mesh, reassemble, [b"\0" * (batch * chunk)])

        pf: Optional[Prefetcher] = None
        controller = None
        tune_stats = None
        tel_summary = None
        tune_on = getattr(cfg, "tune", None) is not None and cfg.tune.enabled
        activation = (
            flight.activate() if flight is not None
            else contextlib.nullcontext()
        )
        t_run0 = time.perf_counter_ns()
        sink_stats: dict = {}
        # Safety net for the per-step adopt/restore pairs below: any
        # abort path that escapes a step between its adopt and restore
        # (a staging error surfacing at enqueue, a stall-guard raise)
        # must not leave a dead step's trace position installed on this
        # thread — every later trace in the process would parent under
        # it (the pod_ingest leak class).
        run_prev_ctx = _tracing.current_trace()
        try:
            with activation:
                if p.readahead > 0:
                    pf = Prefetcher(
                        self.backend, cache, plan,
                        workers=p.prefetch_workers,
                        depth=p.readahead,
                        byte_budget=p.readahead_bytes,
                        transport=tlabel,
                        pool=pool, meter=meter,
                        fetch_fn=routed_fetch if coop is not None else None,
                        # Tuning pre-spawns headroom so the
                        # prefetch_workers knob can grow the live pool
                        # (ceiling shared with the sweep axes).
                        max_workers=(
                            _pf_ceiling(p.prefetch_workers)
                            if tune_on else 0
                        ),
                    )
                    pf.advance(0)
                if tune_on:
                    controller = _build_train_ingest_controller(
                        cfg, fetch_rec, lambda: consumed_bytes,
                        self.backend, pf, len(plan), flight, stager,
                        coop=coop,
                    )
                    if controller is not None:
                        controller.start()
                step_t0 = time.perf_counter_ns()
                for step in range(total_steps):
                    profiler.on_step_begin(step)
                    lo = step * batch
                    keys = plan[lo : lo + batch]
                    op = (
                        step_wf.begin(f"step{step}", tlabel,
                                      install=False, kind="step")
                        if step_wf is not None else None
                    )
                    # The step is its trace's ROOT: every record the
                    # consumer begins inside it (cache hits, demand
                    # misses, peer hops, synchronous stage marks)
                    # parents under the step span — "workload step →
                    # demand read" is the tree's first edge. install=
                    # False keeps the step op out of the phase channel
                    # (reads own it), so the trace position is adopted
                    # explicitly and restored when the step ends.
                    step_prev_ctx = _tracing.current_trace()
                    if op is not None:
                        _tracing.adopt_trace(op.trace_context())
                    stall_ns = 0
                    first_block_ns = last_block_ns = None
                    # Chunk payloads: bytes (legacy arm) or SlabLease
                    # (zero-copy arm). Every entry carries this step's
                    # consumer reference, released after staging.
                    datas: list = []
                    for key in keys:
                        data = cache.get(key)
                        if data is not None:
                            if consumer_wf is not None:
                                cop = consumer_wf.begin(
                                    key.object, tlabel, kind="cache"
                                )
                                cop.mark("cache_hit")
                                cop.finish(len(data))
                        else:
                            cop = (
                                consumer_wf.begin(key.object, tlabel)
                                if consumer_wf is not None else None
                            )
                            t0 = time.perf_counter_ns()
                            if cop is not None:
                                cop.mark("cache_miss", t0)
                            try:
                                data, source = cache.get_or_fetch_info(
                                    key,
                                    lambda k=key: routed_fetch(k),
                                )
                            except BaseException as e:
                                # errgroup semantics (read.py parity): a
                                # demand fetch that still fails after the
                                # whole retry/tail stack aborts the run —
                                # the exception IS the error report.
                                if cop is not None:
                                    cop.finish(error=e)
                                if op is not None:
                                    op.finish(error=e)
                                _tracing.adopt_trace(step_prev_ctx)
                                raise
                            t1 = time.perf_counter_ns()
                            if source == "hit":
                                # Raced hit: a prefetch landed the chunk
                                # between the get() probe and this call.
                                # No wait happened — no stall marks, no
                                # ~0 ms sample in the read histogram,
                                # and the would-be miss record becomes a
                                # cache-hit record (abandon drops it
                                # without appending).
                                if cop is not None:
                                    cop.abandon()
                                    # enqueue_ns=t0: the record spans the
                                    # access from probe to hit — begin()'s
                                    # default "now" stamp would postdate
                                    # t1 and break phase monotonicity.
                                    hop = consumer_wf.begin(
                                        key.object, tlabel, kind="cache",
                                        enqueue_ns=t0,
                                    )
                                    hop.mark("cache_hit", t1)
                                    hop.finish(len(data))
                            else:
                                stall_ns += t1 - t0
                                if first_block_ns is None:
                                    first_block_ns = t0
                                last_block_ns = t1
                                fetch_rec.record_ns(t1 - t0)
                                if cop is not None:
                                    cop.mark("body_complete", t1)
                                    # Bytes credit the fetch OWNER only:
                                    # a coalesced wait consumed bytes
                                    # some other record (the in-flight
                                    # prefetch) already carries — the
                                    # chaos scorecard sums read records,
                                    # and one backend read must count
                                    # once.
                                    cop.finish(
                                        len(data)
                                        if source == "fetched" else 0
                                    )
                        datas.append(data)
                    if op is not None and first_block_ns is not None:
                        op.mark("stall_begin", first_block_ns)
                        op.mark("stall_end", last_block_ns)
                    # ---- stage the batch -------------------------------
                    step_bytes = sum(len(d) for d in datas)
                    if p.pod:
                        staged_ns, gathered_ns = self._pod_stage_gather(
                            mesh, reassemble, datas
                        )
                        if op is not None:
                            op.mark("hbm_staged", staged_ns)
                            op.mark("gather_complete", gathered_ns)
                    elif stager is not None:
                        overlapped = getattr(stager, "overlapped", False)
                        can_own = hasattr(stager, "submit_owned")
                        for i, data in enumerate(datas):
                            if (overlapped and can_own
                                    and isinstance(data, SlabLease)):
                                # Overlapped direct staging: the transfer
                                # reads straight out of the pinned slab —
                                # no slot copy — and THIS STEP'S consumer
                                # reference rides with it, released by
                                # the window's reaper only when the bytes
                                # land (never at submit): the fetch/step
                                # thread does not block on the tunnel.
                                stager.submit_owned(data)
                                datas[i] = None
                            else:
                                # The slab view stages IN PLACE: the
                                # sink's slot fill reads straight out of
                                # the pinned slab (no bytes()
                                # materialization between).
                                stager.submit(payload_view(data))
                        if op is not None and not overlapped:
                            # Synchronous staging only: an overlapped
                            # submit returns before the bytes land, so
                            # the step record carries no hbm_staged — the
                            # window's per-transfer stage records stamp
                            # it at true completion (reaper-side).
                            op.mark("hbm_staged")
                    consumed_bytes += step_bytes
                    # Drop the consumer references staging used
                    # synchronously (handed-off leases release at
                    # transfer completion instead) so evicted slabs
                    # retire.
                    for data in datas:
                        if data is not None:
                            release_payload(data)
                    stall_rec.record_ns(stall_ns)
                    if stall_ns > p.stall_threshold_ms * 1e6:
                        stalled_steps += 1
                    # Top the readahead window up BEFORE the compute
                    # window: the prefetcher works while the step
                    # "trains" — that overlap is the whole point.
                    if pf is not None:
                        pf.advance(lo + batch)
                    if (coop is not None and cfg.coop.demote
                            and not coop.lockstep
                            and flight is not None):
                        # Straggler demotion off the run's own per-host
                        # flight tables + locally-observed per-owner
                        # transfer tails (rate-limited inside). NEVER
                        # under a lockstep channel: demotion mutates the
                        # per-host ring from per-host signals, and hosts
                        # whose rings disagree slice different mesh
                        # slots out of the same broadcast — silent
                        # zero-filled chunks. Lockstep pods keep a
                        # static ring.
                        coop.maybe_refresh_demotions(flight)
                    if compute_s:
                        time.sleep(compute_s)
                    if op is not None:
                        op.finish(step_bytes)
                    _tracing.adopt_trace(step_prev_ctx)
                    profiler.on_step_end(step)
                    now = time.perf_counter_ns()
                    step_rec.record_ns(now - step_t0)
                    step_t0 = now
        finally:
            _tracing.adopt_trace(run_prev_ctx)
            profiler.close()
            if controller is not None:
                tune_stats = controller.stop()
            if pf is not None:
                pf.close()
            if coop is not None:
                coop.close()
            if stager is not None:
                sink_stats = stager.finish() or {}
            if tel is not None:
                # stager.finish() above drained the window's reaper, so
                # every stage record has landed: the registry is final.
                # Closed HERE (not after result assembly) so the HTTP
                # server and tick thread never outlive a failed run.
                from tpubench.staging.stats import staging_extra as _sx

                _blk = _sx([sink_stats]) if sink_stats else None
                if p.pod and mesh is not None:
                    tel.set_chips(int(mesh.devices.size))
                else:
                    tel.set_chips(int(sink_stats.get("n_chips", 1) or 1))
                tel_summary = tel.close(
                    final_extra={"staging": _blk} if _blk else None
                )
        wall = (time.perf_counter_ns() - t_run0) / 1e9

        # ------------------------------------------------------- result ----
        stall_arr = stall_rec.as_ns_array()
        pipe_extra = {
            "cache": cache.stats(),
            "prefetch": pf.stats() if pf is not None else None,
            "stall": {
                "steps": total_steps,
                "stalled_steps": stalled_steps,
                "stalled_fraction": (
                    stalled_steps / total_steps if total_steps else 0.0
                ),
                "threshold_ms": p.stall_threshold_ms,
                "total_stall_ms": float(stall_arr.sum() / 1e6),
                "p50_ms": float(np.percentile(stall_arr, 50) / 1e6)
                if stall_arr.size else 0.0,
                "p99_ms": float(np.percentile(stall_arr, 99) / 1e6)
                if stall_arr.size else 0.0,
            },
            "plan": {
                "epochs": p.epochs,
                "steps_per_epoch": p.steps,
                "batch_shards": batch,
                "chunks": len(plan),
                "unique_chunks": len(set(plan)),
                "chunk_bytes": p.chunk_bytes or w.granule_bytes,
            },
        }
        if coop is not None:
            pipe_extra["coop"] = coop.stats()
        # Copies-per-byte: the zero-copy datapath's proof (and the A/B's
        # headline axis) — host-RAM writes of chunk payload per delivered
        # byte; 1.0 = written once off the wire, never copied again.
        copies = meter.stats()
        copies["mode"] = "slab" if pool is not None else "bytes"
        if pool is not None:
            # Teardown order is load-bearing: releasing the cache's lease
            # references BEFORE closing the pool makes leaked_slabs a
            # true leak signal (a resident cache entry is not a leak).
            cache.close()
            pool.close()
            copies["pool"] = pool.stats()
        pipe_extra["copies"] = copies
        summaries = {}
        for name, rec in (
            ("step", step_rec), ("stall", stall_rec), ("read", fetch_rec),
        ):
            if len(rec):
                summaries[name] = summarize_ns(rec.as_ns_array())
        stage_rec = sink_stats.get("stage_recorder")
        if stage_rec is not None and len(stage_rec):
            summaries["stage"] = stage_rec.summarize()
        if p.pod and mesh is not None:
            # Pod path has no stager stats: the batch was staged across
            # the whole mesh (pod_ingest parity — per-chip bandwidth
            # must divide by the mesh size, not default to 1).
            n_chips = max(1, int(mesh.devices.size))
        else:
            n_chips = max(1, int(sink_stats.get("n_chips", 1)))
        gbps = (consumed_bytes / 1e9) / wall if wall > 0 else 0.0
        # Demand-path failures abort the run (errgroup semantics), so a
        # RunResult only exists for runs whose consumption succeeded;
        # prefetch errors are advisory (the demand path re-fetched) but
        # still degradation — surface them as the run's error count, the
        # same way read.py reports recovered worker failure domains.
        errors = pipe_extra["prefetch"]["errors"] if pf is not None else 0
        res = RunResult(
            workload="train_ingest",
            config=cfg.to_dict(),
            bytes_total=consumed_bytes,
            wall_seconds=wall,
            gbps=gbps,
            gbps_per_chip=gbps / n_chips,
            n_chips=n_chips,
            summaries=summaries,
            errors=errors,
        )
        res.extra["pipeline"] = pipe_extra
        if tune_stats is not None:
            res.extra["tune"] = tune_stats
        if tel_summary is not None:
            res.extra["telemetry"] = tel_summary
        prof_info = profiler.info()
        if prof_info is not None:
            res.extra["profile"] = prof_info
        if sink_stats.get("staged_bytes"):
            res.extra["staged_bytes"] = sink_stats["staged_bytes"]
        from tpubench.staging.stats import staging_extra

        staging_block = staging_extra([sink_stats])
        if staging_block is not None:
            res.extra["staging"] = staging_block
        from tpubench.storage.tail import collect_tail_stats

        tail_stats = collect_tail_stats(self.backend)
        if tail_stats:
            res.extra["tail"] = tail_stats
        if flight is not None:
            res.extra["flight"] = flight.summary()
            if jpath_stream:
                res.extra["flight_journal"] = flight.write_journal(
                    jpath_stream,
                    extra={
                        "workload": "train_ingest",
                        "pipeline_copies": pipe_extra["copies"],
                        "n_chips": n_chips,
                        # Pod path stamps the mesh-global chip count (the
                        # same number on every host); the local stager
                        # stamp is per-host.
                        "chips_global": bool(p.pod and mesh is not None),
                    },
                    max_bytes=cfg.obs.journal_max_bytes,
                )
        return res


def _build_train_ingest_controller(cfg, fetch_rec, bytes_fn, backend, pf,
                                   plan_len, flight, stager=None, coop=None):
    """Tune controller for train-ingest: live knobs are the prefetcher's
    readahead depth / byte budget / worker fan-out (Prefetcher.reclamp /
    set_workers), the hedge delay, the overlapped staging executor's
    in-flight depth (stager.set_depth), and the cooperative cache's
    serve budget / on-off routing (coop.set_peer_budget / set_enabled);
    goodput is windowed consumed bytes, the p99 guardrail watches
    demand-fetch latency."""
    from tpubench.storage.tail import HedgedBackend, find_tail_layer
    from tpubench.tune.controller import (
        Knob,
        RecorderSampler,
        TuneController,
        hedge_delay_knob,
        readahead_ceiling,
        staging_depth_ceiling,
    )

    p = cfg.pipeline
    wanted = set(cfg.tune.knobs)
    knobs = []
    if "readahead" in wanted and pf is not None:
        hi = min(readahead_ceiling(p.readahead), max(1, plan_len))
        knobs.append(Knob(
            "readahead", p.readahead,
            lambda v: pf.reclamp(depth=v),
            lo=1, hi=hi, mode="mul",
        ))
    if "readahead_bytes" in wanted and pf is not None \
            and p.readahead_bytes > 0:
        chunk = p.chunk_bytes or cfg.workload.granule_bytes
        knobs.append(Knob(
            "readahead_bytes", p.readahead_bytes,
            lambda v: pf.reclamp(byte_budget=v),
            lo=chunk, hi=8 * p.readahead_bytes, mode="mul",
        ))
    if "prefetch_workers" in wanted and pf is not None:
        hi = pf.stats()["workers_max"]
        if hi > 1:
            knobs.append(Knob(
                "prefetch_workers", pf.active_workers, pf.set_workers,
                lo=1, hi=hi, mode="add",
            ))
    if "hedge_delay_s" in wanted and cfg.transport.tail.hedge:
        hb = find_tail_layer(backend, HedgedBackend)
        if hb is not None:
            knobs.append(hedge_delay_knob(
                cfg.transport.tail.hedge_delay_s, hb.set_hedge_delay,
            ))
    if "staging_depth" in wanted and stager is not None \
            and getattr(stager, "overlapped", False) \
            and hasattr(stager, "set_depth"):
        # In-flight leases come out of the slab pool: an explicitly
        # sized pool caps how far a grow probe may drive the window.
        pool_cap = p.pool_slabs if (p.slab_pool and p.slab_bytes > 0) else 0
        knobs.append(Knob(
            "staging_depth", stager.depth, stager.set_depth,
            lo=1, hi=staging_depth_ceiling(stager.depth, pool_cap),
            mode="mul",
        ))
    if coop is not None and coop.lockstep:
        # Per-host controllers diverge: one host parking at coop=0 stops
        # entering the collectives the others still wait in (mesh hang),
        # and the serve budget is meaningless on the broadcast path.
        # Lockstep routing is not a live knob.
        coop = None
    if "peer_budget_bytes" in wanted and coop is not None \
            and coop.peer_budget_bytes > 0:
        # A configured serve budget is live-resizable; 0 (unbounded) has
        # no meaningful probe neighborhood, so the knob stays inert.
        chunk = p.chunk_bytes or cfg.workload.granule_bytes
        knobs.append(Knob(
            "peer_budget_bytes", coop.peer_budget_bytes,
            coop.set_peer_budget,
            lo=chunk, hi=8 * coop.peer_budget_bytes, mode="mul",
        ))
    if "coop" in wanted and coop is not None:
        # Binary routing knob: the controller may discover that on this
        # pod/workload the peer round-trip loses to origin and park the
        # run at coop=0 (set_enabled takes truthy ints).
        knobs.append(Knob(
            "coop", int(coop.enabled), coop.set_enabled,
            lo=0, hi=1, mode="add",
        ))
    if not knobs:
        return None
    sampler = RecorderSampler([fetch_rec], bytes_fn)
    ring = flight.worker("tune") if flight is not None else None
    return TuneController(cfg.tune, knobs, sampler, flight_ring=ring)


# -------------------------------------------------------------- rendering --


def format_pipeline_scorecard(pipe: dict) -> str:
    """Human rendering of ``extra["pipeline"]`` (printed by the CLI and
    by ``tpubench report`` on train-ingest result files)."""
    stall = pipe.get("stall", {})
    cache = pipe.get("cache", {})
    pf = pipe.get("prefetch")
    plan = pipe.get("plan", {})
    lines = [
        "== ingest-pipeline scorecard ==",
        (
            f"  steps={stall.get('steps', 0)} "
            f"(epochs={plan.get('epochs', '?')}"
            f"×{plan.get('steps_per_epoch', '?')}, "
            f"batch={plan.get('batch_shards', '?')} chunks)"
        ),
        (
            f"  data stalls: stalled_steps={stall.get('stalled_steps', 0)} "
            f"({stall.get('stalled_fraction', 0.0):.1%} of steps over "
            f"{stall.get('threshold_ms', 0)} ms)  "
            f"p50={stall.get('p50_ms', 0.0):.2f} ms  "
            f"p99={stall.get('p99_ms', 0.0):.2f} ms  "
            f"total={stall.get('total_stall_ms', 0.0):.1f} ms"
        ),
    ]
    hr = cache.get("hit_ratio")
    lines.append(
        f"  cache: hits={cache.get('hits', 0)} "
        f"misses={cache.get('misses', 0)} "
        f"coalesced={cache.get('coalesced', 0)} "
        f"hit_ratio={f'{hr:.1%}' if hr is not None else 'n/a'} "
        f"evictions={cache.get('evictions', 0)} "
        f"resident={cache.get('resident_bytes', 0)}B"
        + (
            f" gen_invalidations={cache['generation_invalidations']}"
            if cache.get("generation_invalidations") else ""
        )
    )
    co = pipe.get("coop")
    if co:
        phr = co.get("peer_hit_ratio")
        est = co.get("per_host_origin_estimate_bytes", 0)
        ob = co.get("origin_bytes", 0)
        saved = (1.0 - ob / est) if est else None
        line = (
            f"  coop: hosts={co.get('active_hosts', 0)}"
            f"/{co.get('hosts', 0)} "
            f"peer_hits={co.get('peer_hits', 0)} "
            f"misses={co.get('peer_misses', 0)} "
            f"hit_ratio={f'{phr:.1%}' if phr is not None else 'n/a'} "
            f"pod_coalesced={co.get('pod_coalesced', 0)}  "
            f"origin={ob}B vs per-host-est={est}B"
            + (f" (saved {saved:.1%})" if saved else "")
        )
        if co.get("transfer_p50_ms") is not None:
            line += (
                f"  transfer p50={co['transfer_p50_ms']:.2f} ms "
                f"p99={co['transfer_p99_ms']:.2f} ms"
            )
        if co.get("demotions") or co.get("restores"):
            line += (
                f"  demotions={co.get('demotions', 0)}"
                f"/restores={co.get('restores', 0)}"
            )
        if co.get("budget_rejects"):
            line += f"  budget_rejects={co['budget_rejects']}"
        lines.append(line)
    if pf:
        eff = pf.get("efficiency")
        lines.append(
            f"  prefetch: issued={pf.get('issued', 0)} "
            f"completed={pf.get('completed', 0)} "
            f"skipped={pf.get('skipped', 0)} "
            f"cancelled={pf.get('cancelled', 0)} "
            f"errors={pf.get('errors', 0)}  "
            f"used={pf.get('used_bytes', 0)}B "
            f"wasted={pf.get('wasted_bytes', 0)}B "
            f"efficiency={f'{eff:.1%}' if eff is not None else 'n/a'}"
        )
    else:
        lines.append("  prefetch: off (cold demand reads)")
    cp = pipe.get("copies")
    if cp:
        cpb = cp.get("copies_per_byte")
        line = (
            f"  copies: mode={cp.get('mode', '?')} "
            f"{f'{cpb:.2f}/byte' if cpb is not None else 'n/a'} "
            f"(landed={cp.get('landed_bytes', 0)}B "
            f"copied={cp.get('copied_bytes', 0)}B)"
        )
        pl = cp.get("pool")
        if pl:
            line += (
                f"  pool: slabs={pl.get('slabs', 0)}"
                f"×{pl.get('slab_bytes', 0)}B "
                f"{'pinned' if pl.get('native') else 'bytearray'} "
                f"peak={pl.get('peak_leased', 0)} "
                f"overflow={pl.get('overflow_leases', 0)} "
                f"leaked={pl.get('leaked_slabs', 0)}"
            )
        lines.append(line)
    return "\n".join(lines)
