"""``tpubench tune`` — offline coordinate sweeps and online-adaptive
tuning sessions over the ``read`` / ``train-ingest`` workloads.

The reference asserts its operating point (``--worker 48``,
``main.go:36``); this workload *finds* it, two ways:

* **sweep** — a coordinate sweep in the spirit of the gRPC
  micro-benchmark suite (PAPERS.md, arXiv:1804.01138): one knob axis at
  a time, each candidate a short bounded run, best cell (by goodput,
  subject to the p99 guardrail vs the baseline cell) carried into the
  next axis;
* **online** — one adaptive session: the in-run controller
  (:mod:`tpubench.tune.controller`) moves the knobs live while the
  workload runs, and the convergence trace lands in ``extra["tune"]``;
* **ab** — both, plus the static-vs-adaptive comparison the Pulsar
  study treats as a first-class measured loop (PAPERS.md): adaptive
  converged goodput and p99 against the best static cell.

Hermetic by construction when asked: with ``--protocol http`` and no
endpoint, an in-process fake server (h1.1, or the h2 server under
``--http2``) is spawned carrying the config's fault plan — so shaped
straggler chaos (stall_rate < 1) composes under a tuning session
exactly as it does under ``tpubench chaos``. ``--protocol fake`` is
hermetic via ``open_backend`` as usual; a real endpoint/bucket works
unchanged (real-GCS tuning).

The recommendation is reusable two ways: printed as CLI flags, and
written as a JSON profile (``--tune-profile PATH``) that any later run
applies with the same flag (``tpubench read --tune-profile PATH``).
"""

from __future__ import annotations

import json
import time
from typing import Optional

from tpubench.config import BenchConfig, validate_tune_config
from tpubench.metrics.report import RunResult
from tpubench.tune.controller import ACTUATED

PROFILE_FORMAT = "tpubench-tune-profile-v1"

# Knob name -> CLI flag string (the human-pasteable recommendation).
_KNOB_FLAGS = {
    "workers": "--workers",
    "readahead": "--readahead",
    "readahead_bytes": "--readahead-bytes",
    "prefetch_workers": "--prefetch-workers",
    "hedge_delay_s": "--hedge-delay",
    "staging_depth": "--staging-depth",
}


def _set_path(cfg: BenchConfig, path: tuple, value) -> None:
    obj = cfg
    for name in path[:-1]:
        obj = getattr(obj, name)
    setattr(obj, path[-1], value)


def _get_path(cfg: BenchConfig, path: tuple):
    obj = cfg
    for name in path:
        obj = getattr(obj, name)
    return obj


def apply_knob_values(cfg: BenchConfig, values: dict) -> None:
    """Apply ``{knob name: value}`` onto a config via the ACTUATED
    registry (the same mapping the knob-drift guard pins)."""
    for name, v in values.items():
        spec = ACTUATED.get(name)
        if spec is None:
            raise SystemExit(f"tune: unknown knob {name!r} in profile")
        _set_path(cfg, spec["config"], v)


def load_tune_profile(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    if doc.get("format") != PROFILE_FORMAT:
        raise SystemExit(
            f"{path}: not a tune profile (format={doc.get('format')!r}; "
            f"expected {PROFILE_FORMAT!r})"
        )
    return doc


def apply_tune_profile(cfg: BenchConfig, path: str) -> dict:
    """``--tune-profile`` on a normal workload: overlay the profile's
    recommended knob values onto the config. Returns the values."""
    doc = load_tune_profile(path)
    values = doc.get("recommended") or {}
    apply_knob_values(cfg, values)
    return values


def recommended_flags(values: dict) -> str:
    parts = []
    for name, v in sorted(values.items()):
        flag = _KNOB_FLAGS.get(name)
        if flag:
            parts.append(f"{flag} {v:g}" if isinstance(v, float)
                         else f"{flag} {v}")
    return " ".join(parts)


# ----------------------------------------------------------- sweep axes ---


def _ladder(around: int, lo: int, hi: int) -> list[int]:
    """Doubling ladder through [lo, hi] that includes ``around``."""
    vals = {max(lo, min(hi, around))}
    v = max(lo, 1)
    while v <= hi:
        vals.add(v)
        v *= 2
    return sorted(vals)


def sweep_axes(cfg: BenchConfig, workload: str) -> dict[str, list]:
    """Candidate values per knob axis (intersected with cfg.tune.knobs),
    derived from the config's own operating point."""
    from tpubench.tune.controller import staging_depth_ceiling

    w, p, s, tail = cfg.workload, cfg.pipeline, cfg.staging, cfg.transport.tail
    axes: dict[str, list] = {}
    if workload == "read":
        if w.workers > 1:
            axes["workers"] = _ladder(w.workers, 1, w.workers)
        if tail.hedge:
            d = tail.hedge_delay_s
            axes["hedge_delay_s"] = sorted({d / 4, d / 2, d, d * 2})
    else:  # train-ingest
        from tpubench.tune.controller import (
            prefetch_workers_ceiling,
            readahead_ceiling,
        )

        if p.readahead > 0:
            axes["readahead"] = _ladder(
                p.readahead, 1, readahead_ceiling(p.readahead)
            )
        axes["prefetch_workers"] = _ladder(
            p.prefetch_workers, 1, prefetch_workers_ceiling(p.prefetch_workers)
        )
        if tail.hedge:
            d = tail.hedge_delay_s
            axes["hedge_delay_s"] = sorted({d / 4, d / 2, d, d * 2})
    if s.mode != "none" and s.double_buffer and not p.pod:
        # Same ladder the online knob explores (ceiling single-sourced in
        # controller.py): depth 1 is the serial comparator cell, the rest
        # find the overlap knee. An explicitly sized slab pool caps the
        # ladder — a cell past the pool budget would SystemExit inside
        # run_train_ingest's validate_pipeline_config and kill the sweep.
        pool_cap = (
            p.pool_slabs
            if (workload != "read" and p.slab_pool and p.slab_bytes > 0)
            else 0
        )
        axes["staging_depth"] = _ladder(
            max(1, s.depth), 1,
            staging_depth_ceiling(max(1, s.depth), pool_cap),
        )
    if cfg.coop.enabled and workload != "read" \
            and cfg.coop.channel != "ici":
        # The routing switch is a 2-cell axis (the sweep's answer to "is
        # the peer round-trip worth it on this pod/workload"); a
        # configured serve budget sweeps the same neighborhood the
        # online knob probes (0 = unbounded has no neighborhood). Only
        # train-ingest builds a CoopCache (a read-workload coop axis
        # would sweep identical-noise cells), and lockstep routing is
        # not a knob (see _build_train_ingest_controller).
        axes["coop"] = [0, 1]
        b = cfg.coop.peer_budget_bytes
        if b > 0:
            axes["peer_budget_bytes"] = sorted({b // 2, b, b * 2, b * 4})
    wanted = set(cfg.tune.knobs)
    return {k: v for k, v in axes.items() if k in wanted}


# ------------------------------------------------------------ execution ---


def _run_target(cfg: BenchConfig, workload: str, tracer=None) -> RunResult:
    if workload == "read":
        from tpubench.workloads.read import run_read

        return run_read(cfg, tracer=tracer)
    if workload == "train-ingest":
        from tpubench.workloads.train_ingest import run_train_ingest

        return run_train_ingest(cfg)
    raise SystemExit(f"tune: unknown workload {workload!r} "
                     "(read|train-ingest)")


def _cell_stats(res: RunResult) -> dict:
    s = res.summaries.get("read")
    return {
        "goodput_bps": res.gbps * 1e9,
        "p99_ms": s.p99_ms if s is not None else None,
        "wall_s": res.wall_seconds,
        "errors": res.errors,
    }


def _clone(cfg: BenchConfig) -> BenchConfig:
    return BenchConfig.from_dict(cfg.to_dict())


def run_sweep(cfg: BenchConfig, workload: str,
              before_run=None) -> dict:
    """Offline coordinate sweep: baseline cell at the config's own
    operating point, then one axis at a time, carrying the best
    admissible cell's value forward. A cell whose p99 exceeds the
    guardrail (vs the baseline cell) is recorded but never selected.
    ``before_run`` fires before every cell (the hermetic fault plan's
    per-run re-arm)."""
    tc = cfg.tune
    axes = sweep_axes(cfg, workload)
    current: dict = {
        name: _get_path(cfg, ACTUATED[name]["config"]) for name in axes
    }
    cells: list[dict] = []

    def run_cell(values: dict) -> dict:
        c = _clone(cfg)
        c.tune.enabled = False
        # One short cell per knob value: a dozen bind/teardown cycles of
        # the telemetry HTTP endpoint (and OTLP flush loops) are churn,
        # not signal — the plane stays on for the ONLINE/adaptive arm,
        # which is the long-lived run `tpubench top` watches.
        c.telemetry.enabled = False
        c.telemetry.port = -1
        c.telemetry.otlp = False
        apply_knob_values(c, values)
        if before_run is not None:
            before_run()
        t0 = time.monotonic()
        res = _run_target(c, workload)
        cell = {
            "values": dict(values),
            **_cell_stats(res),
            "sweep_wall_s": time.monotonic() - t0,
        }
        cells.append(cell)
        return cell

    base = run_cell(dict(current))
    base_p99 = base["p99_ms"]
    best = base

    def admissible(cell: dict) -> bool:
        if cell["errors"]:
            return False
        if base_p99 and cell["p99_ms"]:
            return cell["p99_ms"] <= tc.p99_guard * base_p99
        return True

    for name, candidates in axes.items():
        axis_best = best
        for v in candidates:
            if v == current[name]:
                continue
            cell = run_cell({**current, name: v})
            if admissible(cell) and (
                cell["goodput_bps"] > axis_best["goodput_bps"]
            ):
                axis_best = cell
        best = axis_best
        current = dict(best["values"])
    return {
        "axes": {k: list(v) for k, v in axes.items()},
        "cells": cells,
        "baseline": base,
        "best": best,
        "guard": {"p99_guard": tc.p99_guard, "baseline_p99_ms": base_p99},
    }


def run_tune(
    cfg: BenchConfig,
    mode: str = "online",
    workload: str = "read",
    profile_path: str = "",
    tracer=None,
) -> RunResult:
    """The ``tpubench tune`` entry point (module docstring).

    ``tracer`` (built and flush-on-exit-closed by the CLI's
    ``tracer_session``) instruments the ONLINE/adaptive arm — the
    long-lived run whose spans are worth exporting. Sweep cells stay
    untraced, the same churn-not-signal policy that disables their
    telemetry endpoint."""
    validate_tune_config(cfg.tune)
    if mode not in ("sweep", "online", "ab"):
        raise SystemExit(f"tune: unknown mode {mode!r} (sweep|online|ab)")

    # Hermetic server (chaos parity): --protocol http with no endpoint
    # spawns the in-process fake server carrying the config's fault plan
    # — shaped straggler chaos under a tuning session.
    server = None
    plan = None
    endpoint_restore = cfg.transport.endpoint
    try:
        if cfg.transport.protocol == "http" and not cfg.transport.endpoint:
            import dataclasses

            from tpubench.storage.fake import FaultPlan
            from tpubench.workloads.chaos import spawn_hermetic_server

            if cfg.transport.fault.active:
                plan = FaultPlan(**dataclasses.asdict(cfg.transport.fault))
                plan.arm()
            server = spawn_hermetic_server(cfg, fault_plan=plan)

        def rearm() -> None:
            # Time-phased fault schedules are relative to a run's start:
            # re-arm before EVERY target run, or only the earliest sweep
            # cells would see the fault window and the static-vs-adaptive
            # comparison would measure different conditions per cell.
            if plan is not None:
                plan.arm()

        tune_extra: dict = {"mode": mode, "workload": workload}
        adaptive_res: Optional[RunResult] = None
        if mode in ("sweep", "ab"):
            tune_extra["sweep"] = run_sweep(cfg, workload, before_run=rearm)
        if mode in ("online", "ab"):
            c = _clone(cfg)
            c.tune.enabled = True
            rearm()
            adaptive_res = _run_target(c, workload, tracer=tracer)
            tune_extra["adaptive"] = adaptive_res.extra.get("tune") or {
                "enabled": False,
                "note": "workload had no live-actuatable knobs",
            }
            tune_extra["adaptive_run"] = _cell_stats(adaptive_res)

        # The recommendation: the adaptive session's converged point
        # when one ran, else the sweep's best cell.
        if adaptive_res is not None and tune_extra["adaptive"].get("final"):
            recommended = dict(tune_extra["adaptive"]["final"])
        elif tune_extra.get("sweep"):
            recommended = dict(tune_extra["sweep"]["best"]["values"])
        else:
            recommended = {}
        tune_extra["recommended"] = recommended
        tune_extra["recommended_flags"] = recommended_flags(recommended)

        if mode == "ab" and tune_extra.get("sweep"):
            best = tune_extra["sweep"]["best"]
            ad = tune_extra["adaptive"]
            ad_good = (
                ad.get("converged_goodput_bps")
                or tune_extra["adaptive_run"]["goodput_bps"]
            )
            ab = {
                "static_best_values": best["values"],
                "static_best_goodput_bps": best["goodput_bps"],
                "static_best_p99_ms": best["p99_ms"],
                "adaptive_values": recommended,
                "adaptive_goodput_bps": ad_good,
                "adaptive_p99_ms": (
                    ad.get("converged_p99_ms")
                    or tune_extra["adaptive_run"]["p99_ms"]
                ),
            }
            if best["goodput_bps"]:
                ab["goodput_vs_static_best"] = (
                    ad_good / best["goodput_bps"] if ad_good else None
                )
            tune_extra["ab"] = ab

        if profile_path:
            doc = {
                "format": PROFILE_FORMAT,
                "workload": workload,
                "mode": mode,
                "recommended": recommended,
                "flags": tune_extra["recommended_flags"],
                "created": time.time(),
            }
            with open(profile_path, "w") as f:
                json.dump(doc, f, indent=2)
            tune_extra["profile"] = profile_path

        # The RunResult: the adaptive run's numbers when one ran (the
        # session IS a run), else a thin carrier for the sweep table.
        if adaptive_res is not None:
            res = adaptive_res
            res.workload = "tune"
        else:
            best = tune_extra["sweep"]["best"]
            res = RunResult(
                workload="tune",
                config=cfg.to_dict(),
                gbps=best["goodput_bps"] / 1e9,
                summaries={},
            )
        res.extra["tune"] = tune_extra
        return res
    finally:
        if server is not None:
            server.stop()
        cfg.transport.endpoint = endpoint_restore


# -------------------------------------------------------------- rendering --


def format_tune_block(tune: dict) -> str:
    """Human rendering of a tune result's ``extra["tune"]`` (printed by
    the CLI and by ``tpubench report``): convergence trace summary,
    sweep table, recommendation, and the static-vs-adaptive delta."""
    lines = [f"== tune ({tune.get('mode', '?')} over "
             f"{tune.get('workload', '?')}) =="]
    sweep = tune.get("sweep")
    if sweep:
        lines.append("  static sweep (goodput GB/s @ p99 ms):")
        for cell in sweep.get("cells", ()):
            vals = " ".join(
                f"{k}={v:g}" if isinstance(v, float) else f"{k}={v}"
                for k, v in sorted(cell["values"].items())
            )
            p99 = cell.get("p99_ms")
            lines.append(
                f"    {vals:<40} {cell['goodput_bps'] / 1e9:.4f} GB/s @ "
                + (f"{p99:.2f} ms" if p99 is not None else "n/a")
            )
        best = sweep.get("best", {})
        lines.append(
            f"  best static cell: {best.get('values')} "
            f"({best.get('goodput_bps', 0) / 1e9:.4f} GB/s)"
        )
    ad = tune.get("adaptive")
    if ad and ad.get("enabled"):
        conv = ad.get("windows_to_converge")
        lines.append(
            "  adaptive: "
            + (f"converged in {conv} windows" if ad.get("converged")
               else f"NOT converged ({ad.get('n_windows', 0)} windows)")
            + f"  accepts={ad.get('accepts', 0)}"
              f" reverts={ad.get('reverts', 0)}"
              f" guard_violations={ad.get('guard_violations', 0)}"
        )
        lines.append(
            f"    operating point: {ad.get('initial')} -> {ad.get('final')}"
        )
        cg = ad.get("converged_goodput_bps")
        cp = ad.get("converged_p99_ms")
        if cg is not None:
            lines.append(
                f"    converged goodput: {cg / 1e9:.4f} GB/s"
                + (f"  p99 {cp:.2f} ms" if cp is not None else "")
            )
    ab = tune.get("ab")
    if ab:
        ratio = ab.get("goodput_vs_static_best")
        lines.append(
            "  static-vs-adaptive: adaptive "
            f"{(ab.get('adaptive_goodput_bps') or 0) / 1e9:.4f} GB/s vs "
            f"best static {(ab.get('static_best_goodput_bps') or 0) / 1e9:.4f}"
            f" GB/s"
            + (f" ({ratio:.3f}x)" if ratio is not None else "")
        )
        sp, ap = ab.get("static_best_p99_ms"), ab.get("adaptive_p99_ms")
        if sp is not None and ap is not None:
            lines.append(
                f"    p99 delta: adaptive {ap:.2f} ms vs static {sp:.2f} ms "
                f"({ap - sp:+.2f} ms)"
            )
    rec = tune.get("recommended")
    if rec:
        lines.append(f"  recommended: {rec}")
        if tune.get("recommended_flags"):
            lines.append(f"    flags: {tune['recommended_flags']}")
        if tune.get("profile"):
            lines.append(
                f"    profile: {tune['profile']} "
                "(reuse: --tune-profile <path>)"
            )
    return "\n".join(lines)
